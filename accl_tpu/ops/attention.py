"""Blockwise online-softmax attention — the flagship's fused path.

The naive form materializes the (T, T) score matrix per head through
``jax.nn.softmax``: at the flagship bench shape (B=8, H=32, T=1024,
bf16) that is ~0.5 GB of HBM score traffic per layer, pure bandwidth
with no MXU work — the memory ceiling the reference's datapath never
pays because its reduce pipeline streams.  This module computes the
same attention as a scan of (block_q x block_k) tiles with the running
(max, denominator, numerator) state of online softmax [Milakov &
Gimelshein; FlashAttention]: per-tile intermediates stay in registers/
VMEM-sized values, HBM sees only q/k/v/o.

Fully differentiable (the scans are plain lax control flow) and
remat-annotated per q-block, so the backward recomputes tiles instead
of storing them — the same FLOPs-for-HBM trade ``jax.checkpoint`` makes
everywhere else in the stack.

The Pallas form of the same fold (hand-scheduled DMAs, the ring
variant) lives in ``ops/pallas/attention.py``; this XLA form is the
trainable default — every op fuses under jit on any backend.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from .pallas.attention import _mxu_precision

_NEG = -1e30


def _attend_single(q, k, v, causal: bool, bq: int, bk: int, t_real: int):
    """One (T, D) head: scan q blocks; fold k blocks with online softmax.

    ``t_real`` masks padded key positions (T may be padded to block
    multiples by the wrapper)."""
    T, D = q.shape
    nq, nk = T // bq, T // bk
    scale = 1.0 / math.sqrt(D)

    def per_q_block(iq, qb):
        q_pos = iq * bq + jnp.arange(bq)

        def fold(carry, jk):
            m, l, acc = carry
            kb = lax.dynamic_slice_in_dim(k, jk * bk, bk)
            vb = lax.dynamic_slice_in_dim(v, jk * bk, bk)
            # both matmuls run in the INPUT dtype (bf16 hits the MXU's
            # fast path — an f32 upcast here quarters matmul throughput
            # on v5e) with f32 accumulation; the softmax state (m, l,
            # acc) stays f32 for numerical fidelity and the probs cast
            # back down for the p @ v matmul
            s = lax.dot_general(
                qb, kb,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=_mxu_precision(qb.dtype),
            ) * scale  # (bq, bk)
            k_pos = jk * bk + jnp.arange(bk)
            mask = k_pos[None, :] < t_real
            if causal:
                mask &= q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask, s, _NEG)
            m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + p.sum(axis=-1, keepdims=True)
            acc_new = acc * alpha + lax.dot_general(
                p.astype(vb.dtype), vb,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=_mxu_precision(vb.dtype),
            )
            return (m_new, l_new, acc_new), None

        # derive the init from the operand (full_like/zeros_like) so its
        # varying-manual-axes type matches the fold output under
        # shard_map — fresh constants would be axis-invariant and fail
        # the scan carry check
        init = (
            jnp.full_like(qb[:, :1], _NEG, dtype=jnp.float32),
            jnp.zeros_like(qb[:, :1], dtype=jnp.float32),
            jnp.zeros_like(qb, dtype=jnp.float32),
        )
        (m, l, acc), _ = lax.scan(fold, init, jnp.arange(nk))
        return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)

    # remat per q-block: the backward re-folds the tiles instead of
    # keeping every (bq, bk) p matrix alive
    per_q_block = jax.checkpoint(per_q_block, static_argnums=())
    out = jax.vmap(per_q_block)(
        jnp.arange(nq), q.reshape(nq, bq, D)
    )
    return out.reshape(T, D)


def blockwise_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int = 256,
    block_k: int = 256,
) -> jax.Array:
    """Causal (or full) attention over ``(B, H, T, Dh)`` operands without
    materializing the (T, T) score matrix.  Exact (not approximate):
    matches the naive softmax form to float tolerance.

    Block sizes clamp to the (padded) sequence length; T is padded to a
    block multiple internally and the pad keys are masked out.

    Grouped-query attention: k/v may carry fewer heads (``H % Hkv == 0``);
    they are expanded logically (broadcast per group) before the fold —
    the XLA form pays the expansion in activation reads, the Pallas flash
    kernel's index-map sharing avoids it."""
    B, H, T, Dh = q.shape
    Hkv = k.shape[1]
    if Hkv != H:
        if Hkv <= 0 or H % Hkv:
            raise ValueError(
                f"q heads ({H}) must be a multiple of kv heads ({Hkv})"
            )
        G = H // Hkv
        k = jnp.broadcast_to(
            k[:, :, None], (B, Hkv, G, T, Dh)
        ).reshape(B, H, T, Dh)
        v = jnp.broadcast_to(
            v[:, :, None], (B, Hkv, G, T, Dh)
        ).reshape(B, H, T, Dh)
    bq = min(block_q, T) if T > 0 else block_q
    bk = min(block_k, T) if T > 0 else block_k
    pad = (-T) % max(bq, bk)
    # one common padded length keeps both block counts integral
    Tp = T + pad
    bq = min(bq, Tp)
    bk = min(bk, Tp)
    if Tp % bq:
        bq = Tp  # tiny sequences: single block
    if Tp % bk:
        bk = Tp
    if pad:
        padding = [(0, 0), (0, 0), (0, pad), (0, 0)]
        q = jnp.pad(q, padding)
        k = jnp.pad(k, padding)
        v = jnp.pad(v, padding)
    single = functools.partial(
        _attend_single, causal=causal, bq=bq, bk=bk, t_real=T
    )
    out = jax.vmap(jax.vmap(single))(q, k, v)
    return out[:, :, :T]
