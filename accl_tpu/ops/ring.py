"""Algorithm-faithful ring collectives: explicit ppermute pipelines.

The reference's headline allreduce is a *segmented ring reduce-scatter +
ring allgather* executed by the firmware against the FPGA dataplane
(``ccl_offload_control.c:1888-2071``, with block/tail handling at
:1900-1912 and fused recv-reduce-send hops).  XLA's built-in collectives
normally make this choice for us; this module exposes the same algorithm as
an explicit ``lax.ppermute`` pipeline so the reference's tuning surface
(block layout, segment count, hop structure) stays programmable — the basis
for overlap-style schedules (ring attention et al.) layered on top.

All functions run inside ``shard_map`` over a named axis.  Every hop is a
static-permutation ``collective-permute``, which on TPU maps to neighbor
DMAs over ICI.
"""

from __future__ import annotations

from functools import partial

import jax

from ..compat import install as _compat_install

_compat_install()  # legacy-jax shims (shard_map kwargs, lax.axis_size)
import jax.numpy as jnp
from jax import lax

from ..constants import ReduceFunction


def _combine(function: ReduceFunction):
    if function == ReduceFunction.SUM:
        return jnp.add
    if function == ReduceFunction.MAX:
        return jnp.maximum
    raise ValueError(f"unsupported reduce function {function}")


def _next_perm(size: int):
    return [(i, (i + 1) % size) for i in range(size)]


def _pad_to_blocks(x: jax.Array, size: int):
    n = x.shape[0]
    block = -(-n // size)
    pad = block * size - n
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,) + x.shape[1:], x.dtype)])
    return x.reshape((size, block) + x.shape[1:]), block, pad


def ring_reduce_scatter(
    x: jax.Array,
    axis_name: str,
    function: ReduceFunction = ReduceFunction.SUM,
) -> jax.Array:
    """Ring reduce-scatter: P-1 hops, each a fused recv-reduce-send
    (ref c:1782-1851).  Input: the full local operand (same shape on every
    rank).  Output: this rank's reduced block (padded size n/P)."""
    size = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    op = _combine(function)
    blocks, block, _ = _pad_to_blocks(x, size)
    perm = _next_perm(size)

    def take(b, c):
        return lax.dynamic_slice_in_dim(b, (c % size) * block, block, axis=0)

    # step 1 sends own block (idx-1); step s accumulates chunk (idx-1-s)
    send = take(blocks.reshape((-1,) + x.shape[1:]), idx - 1)

    def body(s, send):
        recv = lax.ppermute(send, axis_name, perm)
        c = idx - 1 - s
        return op(recv, take(blocks.reshape((-1,) + x.shape[1:]), c))

    acc = lax.fori_loop(1, size, body, send) if size > 1 else send
    return acc  # rank idx holds reduced block idx


def ring_allgather(x: jax.Array, axis_name: str) -> jax.Array:
    """Ring allgather: store-and-relay around the ring (ref c:1402-1500).
    Input: this rank's block; output: all blocks concatenated."""
    size = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    block = x.shape[0]
    perm = _next_perm(size)
    out = jnp.zeros((size * block,) + x.shape[1:], x.dtype)
    out = lax.dynamic_update_slice_in_dim(out, x, idx * block, axis=0)

    def body(s, carry):
        out, send = carry
        recv = lax.ppermute(send, axis_name, perm)
        origin = jnp.mod(idx - 1 - s, size)
        out = lax.dynamic_update_slice_in_dim(out, recv, origin * block, axis=0)
        return out, recv

    if size > 1:
        out, _ = lax.fori_loop(0, size - 1, body, (out, x))
    return out


def ring_allreduce(
    x: jax.Array,
    axis_name: str,
    function: ReduceFunction = ReduceFunction.SUM,
    num_segments: int = 1,
) -> jax.Array:
    """Segmented ring allreduce = ring reduce-scatter + ring allgather
    (ref allreduce c:1888-2071).

    ``num_segments`` splits every block transfer into independent segment
    pipelines (the reference's eager segmentation / dm_seg tuning knob):
    segment pipelines interleave across hops, overlapping wire time with
    reduce time.  With 1 segment this is the classic 2(P-1)-hop ring."""
    n = x.shape[0]
    size = lax.axis_size(axis_name)
    if size == 1:
        return x
    if num_segments > 1:
        segs = _pad_to_blocks(x, num_segments)[0]
        out = jax.vmap(
            lambda seg: ring_allreduce(seg, axis_name, function, 1),
            spmd_axis_name=axis_name,
        )(segs)
        return out.reshape(-1)[:n]
    acc = ring_reduce_scatter(x, axis_name, function)
    full = ring_allgather(acc, axis_name)
    return full[:n]


def ring_pipeline(
    x: jax.Array,
    axis_name: str,
    step_fn,
    steps: int,
) -> jax.Array:
    """Generic ring schedule: repeatedly shift a buffer to the next neighbor
    and fold it with ``step_fn(carry, received, step)`` — the composable
    substrate for overlap patterns (ring attention-style consumers build on
    this the way the reference exposes its segmented ring machinery)."""
    size = lax.axis_size(axis_name)
    perm = _next_perm(size)

    def body(s, carry):
        state, send = carry
        recv = lax.ppermute(send, axis_name, perm)
        state = step_fn(state, recv, s)
        return state, recv

    state, _ = lax.fori_loop(0, steps, body, (x, x))
    return state
