"""accl_tpu.ops: the idiomatic TPU collective layer.

Pure-functional JAX collectives in two flavors:

* ``collectives`` — XLA's native collectives (psum / all_gather /
  psum_scatter / all_to_all / ppermute) wrapped with the reference op
  vocabulary, for use inside ``shard_map``/``pjit`` over a Mesh.  This is
  the fast path: XLA schedules the ICI transfers.
* ``ring`` — explicit, segment-controlled ring pipelines built from
  ``lax.ppermute`` (algorithm-faithful mode, mirroring the reference
  firmware's ring reduce-scatter + allgather allreduce,
  ccl_offload_control.c:1888-2071), for when you need the reference's
  tuning surface (segment sizes, overlap) rather than XLA's choices.
* ``pallas`` — hand-written TPU kernels for the dataplane hot ops: the
  reduce_ops/hp_compression plugins as VMEM-tiled VPU passes, and the
  segmented ring collectives as single Pallas kernels whose hops are
  Mosaic remote DMAs over ICI with slot-ack flow control (the RX-buffer
  release protocol).  Off-TPU they execute under the Pallas TPU
  interpreter, optionally with its vector-clock race detector.

The ``driver`` module wraps both in host-level helpers that take global
arrays and a Mesh and run the jitted SPMD program.
"""

from . import collectives, overlap, pallas, ring  # noqa: F401
from .driver import (  # noqa: F401
    make_mesh,
    run_allgather,
    run_allreduce,
    run_alltoall,
    run_bcast,
    run_gather,
    run_reduce,
    run_reduce_scatter,
    run_ring_allreduce,
    run_scatter,
)
