"""Device-side twin of the host wire codec (:mod:`accl_tpu.wire`).

Bit-identical jnp forms of the quantized wire lanes — the sequencer
decode loops (both lowerings), the compressed-allreduce program and the
dist tier's in-program wire rounding all call THESE, and
tests/test_wire.py holds them to byte equality against the numpy codec
(same input, same seed -> same wire bytes).  Bit identity is why every
operation here is integer arithmetic or IEEE-exact float arithmetic
(division, floor, rint, absmax): nothing depends on accumulation order
or platform-specific rounding.

Seeds are int32 SCALARS (traced values, typically read from a command-
ring slot's ``flags`` word) — programs never recompile on seed churn.
Rank mixing (:func:`rank_seed`) runs on device from ``axis_index`` so
one rank-identical slot encoding still gives every rank an independent
SR stream.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..constants import (
    WIRE_SEGMENT_ELEMS,
    DataType,
)
from ..wire import dropped_mantissa_bits, is_scaled, lane_tiny, seg_count

__all__ = [
    "dequantize_int8",
    "quantize_int8",
    "rank_seed",
    "sr_bits",
    "wire_lane_roundtrip",
]


def rank_seed(seed, rank):
    """jnp twin of :func:`accl_tpu.wire.rank_seed` (scalar uint32
    arithmetic; seed 0 stays 0 = deterministic)."""
    seed = jnp.asarray(seed).astype(jnp.uint32)
    h = seed ^ (jnp.asarray(rank).astype(jnp.uint32) * jnp.uint32(0x9E3779B9))
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    h = jnp.where(h == 0, jnp.uint32(1), h)
    return jnp.where(seed == 0, jnp.uint32(0), h)


def sr_bits(n: int, seed) -> jax.Array:
    """jnp twin of :func:`accl_tpu.wire.sr_bits`: ``n`` uniform uint32
    draws from the Murmur3 finalizer of ``(index, seed)``."""
    h = (
        jnp.arange(n, dtype=jnp.uint32) * jnp.uint32(2654435761)
    ) ^ jnp.asarray(seed).astype(jnp.uint32)
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> jnp.uint32(16))
    return h


def _cast_lane(x, wire_dtype, seed):
    """f32 -> narrow float wire values, SR when ``seed`` is nonzero
    (the numpy codec's bit-trick, traced: mask-add-truncate on the
    dropped mantissa bits, deterministic fallback for non-finite /
    subnormal-of-target values).  ``seed == 0`` is a plain RTNE cast —
    the branch is traced on VALUES via where, so one program serves
    both (seed is data)."""
    wire_dtype = jnp.dtype(wire_dtype)
    from ..constants import numpy_to_dtype

    dt = numpy_to_dtype(wire_dtype)
    drop = dropped_mantissa_bits(dt)
    x32 = x.astype(jnp.float32)
    mask = jnp.uint32((1 << drop) - 1)
    bits = sr_bits(x32.size, seed).reshape(x32.shape) & mask
    u = lax.bitcast_convert_type(x32, jnp.uint32)
    rounded = lax.bitcast_convert_type((u + bits) & ~mask, jnp.float32)
    use_sr = (
        jnp.isfinite(x32)
        & (jnp.abs(x32) >= jnp.float32(lane_tiny(dt)))
        & (jnp.asarray(seed).astype(jnp.uint32) != 0)
    )
    return jnp.where(use_sr, rounded, x32).astype(wire_dtype)


def quantize_int8(x, seed) -> Tuple[jax.Array, jax.Array]:
    """jnp twin of the scaled int8 lane encode: ``(q int8, scales
    f32)`` with one absmax/127 scale per WIRE_SEGMENT_ELEMS block.
    ``seed`` nonzero: ``floor(x/scale + u)``; zero: ``rint`` — traced
    as data through where, like the cast lane."""
    x32 = x.astype(jnp.float32).reshape(-1)
    n = x32.shape[0]
    nseg = seg_count(n)
    pad = nseg * WIRE_SEGMENT_ELEMS - n
    if pad:
        x32 = jnp.concatenate([x32, jnp.zeros((pad,), jnp.float32)])
    m = x32.reshape(nseg, WIRE_SEGMENT_ELEMS)
    scales = jnp.maximum(
        jnp.max(jnp.abs(m), axis=1) / jnp.float32(127.0),
        jnp.float32(1e-30),
    )
    q_real = m / scales[:, None]
    u = (
        sr_bits(m.size, seed).reshape(m.shape).astype(jnp.float32)
        * jnp.float32(1.0 / 4294967296.0)
    )
    q_sr = jnp.floor(q_real + u)
    q_det = jnp.round(q_real)  # half-to-even, = np.rint
    stochastic = jnp.asarray(seed).astype(jnp.uint32) != 0
    q = jnp.where(stochastic, q_sr, q_det)
    q = jnp.clip(q, -127, 127).astype(jnp.int8).reshape(-1)[:n]
    return q, scales


def dequantize_int8(q, scales, n: int, out_dtype=jnp.float32) -> jax.Array:
    """jnp twin of the scaled int8 lane decode."""
    nseg = scales.shape[0]
    pad = nseg * WIRE_SEGMENT_ELEMS - n
    qf = q.astype(jnp.float32)
    if pad:
        qf = jnp.concatenate([qf, jnp.zeros((pad,), jnp.float32)])
    out = (qf.reshape(nseg, WIRE_SEGMENT_ELEMS) * scales[:, None]).reshape(
        -1
    )[:n]
    return out.astype(out_dtype)


def wire_lane_roundtrip(x, wire_dtype, seed=0):
    """One in-program wire rounding lane: narrow to ``wire_dtype`` (SR
    when ``seed`` is a nonzero traced scalar), widen back to ``x``'s
    dtype — the single-rounding semantic the decode loops and the
    compressed-allreduce program run per contribution, covering EVERY
    registered lane (cast lanes by dtype, the scaled int8 lane by
    blockwise quantization).  THE shared lane helper: both sequencer
    lowerings must route their wire casts through here (the acclint
    ``cmdring-slot-layout`` wire cross-check enforces it)."""
    wire_np = jnp.dtype(wire_dtype)
    orig = x.dtype
    from ..constants import numpy_to_dtype

    dt = numpy_to_dtype(wire_np)
    if is_scaled(dt):
        shape = x.shape
        q, scales = quantize_int8(x, seed)
        return dequantize_int8(
            q, scales, int(x.size), out_dtype=orig
        ).reshape(shape)
    if dropped_mantissa_bits(dt) is not None:
        return _cast_lane(x, wire_np, seed).astype(orig)
    return x.astype(wire_np).astype(orig)


#: lane-kind table for the registered wire dtypes (numpy-name keyed):
#: "cast" lanes narrow by dtype, "scaled" lanes quantize blockwise.
#: Parsed by the acclint wire cross-check against
#: constants.WIRE_LANE_DTYPES — a registered lane missing here is a
#: finding before it is a workload fallback.
WIRE_LANES = {
    "float16": "cast",
    "bfloat16": "cast",
    "float8_e4m3fn": "cast",
    "float8_e5m2": "cast",
    "int8": "scaled",
}
