"""Shared plumbing for the Pallas kernel tier.

The kernels in this package are the TPU-native re-design of the reference's
HLS dataplane plugins (reduce_ops, hp_compression — /root/reference
kernels/plugins/) and of the segmented-ring hot loop the firmware drives
through the dma_mover (ccl_offload_control.c:1888-2071): instead of AXIS
streams through a 512-bit switch, data moves HBM->VMEM->VPU in (rows, 128)
lane tiles, and inter-chip hops are Mosaic remote DMAs over ICI.

Every public kernel takes ``interpret=None``: on a real TPU it compiles via
Mosaic; elsewhere it runs under the Pallas TPU interpreter
(``pltpu.InterpretParams``), which is how the CI tier (virtual CPU mesh)
executes the very same kernels — the role the reference's x86-compiled HLS
emulator plays for its hardware dataplane.
"""

from __future__ import annotations

from typing import Optional, Union

import jax
import jax.numpy as jnp
from jax.experimental.pallas import tpu as pltpu

# TPU vector lane width: last dim of every tile is 128 lanes.
LANES = 128
# Sublane padding that satisfies every dtype's minimum tile (f32 needs 8,
# bf16/f16 16, int8 32 — pad rows to the worst case).
SUBLANES = 32

InterpretArg = Union[None, bool, "pltpu.InterpretParams"]


def sublanes_for(dtype) -> int:
    """Minimum sublane multiple for a dtype's VMEM tile (second-to-last
    dim): f32 8, bf16/f16 16, int8/fp8 32."""
    import jax.numpy as jnp

    return {4: 8, 2: 16, 1: 32}.get(jnp.dtype(dtype).itemsize, 8)


def default_interpret(interpret: InterpretArg = None):
    """Resolve the ``interpret`` argument: explicit values pass through;
    ``None`` selects compiled Mosaic on TPU and the TPU interpreter on any
    other backend (the CI tier)."""
    if interpret is not None:
        return interpret
    if jax.default_backend() == "tpu":
        return False
    return pltpu.InterpretParams()


def mosaic_rejects(interpret_resolved, *dtypes) -> bool:
    """True when ``interpret_resolved`` (the output of
    :func:`default_interpret`) selects compiled Mosaic and any of
    ``dtypes`` is float16.  The TPU mosaic dialect has no ``f16``
    (measured on v5e: the AOT compile rejects the kernel with
    "Unsupported type in mosaic dialect: 'f16'", and a failed remote
    compile aborts the whole client session) — so every kernel entry
    point must reroute to XLA or raise BEFORE ``pallas_call``.  ``None``
    entries are ignored; the interpreter tier handles f16 fine."""
    if interpret_resolved:
        return False
    f16 = jnp.dtype(jnp.float16)
    return any(d is not None and jnp.dtype(d) == f16 for d in dtypes)


def require_mosaic_dtypes(interpret_resolved, which: str, *dtypes) -> None:
    """Raise the shared f16 rejection for kernels with no XLA reroute
    (remote-DMA / fused-compute programs): one message, one rule, every
    entry point."""
    if mosaic_rejects(interpret_resolved, *dtypes):
        raise ValueError(
            f"float16 operands are not supported by the compiled {which} "
            "kernel (the TPU mosaic dialect has no f16); use bfloat16"
        )


def pack_lanes(x: jax.Array, min_rows: int = SUBLANES):
    """Flatten ``x`` and pad it into a (rows, LANES) tile-aligned 2-D array.

    Returns ``(packed, n)`` where ``n`` is the original element count;
    ``unpack_lanes`` inverts it.  Zero padding is benign for every wire/
    arith op in this package (pads are sliced off before results are used).
    """
    flat = x.reshape(-1)
    n = flat.shape[0]
    rows = -(-n // LANES)
    rows = max(-(-rows // min_rows), 1) * min_rows  # >=1 tile even for n=0
    pad = rows * LANES - n
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), x.dtype)])
    return flat.reshape(rows, LANES), n


def unpack_lanes(packed: jax.Array, n: int, shape, dtype=None) -> jax.Array:
    out = packed.reshape(-1)[:n].reshape(shape)
    return out.astype(dtype) if dtype is not None else out


def block_rows(total_rows: int, want: int = 512) -> int:
    """Pick a grid block height: a divisor of ``total_rows`` close to
    ``want`` that keeps tiles sublane-aligned."""
    if total_rows <= want:
        return total_rows
    for cand in range(want, SUBLANES - 1, -SUBLANES):
        if total_rows % cand == 0:
            return cand
    return total_rows


def neighbor_barrier(peer_a, peer_b):
    """Barrier with two (possibly equal) peers before the first remote
    write: signal each peer's global barrier semaphore, wait for both of
    ours — the precondition that the remote comm scratch exists before
    data lands in it.  Requires ``collective_id`` in the kernel's
    CompilerParams."""
    sem = pltpu.get_barrier_semaphore()
    for peer in (peer_a, peer_b):
        pltpu.semaphore_signal(
            sem, inc=1, device_id=peer,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
    pltpu.semaphore_wait(sem, 2)


def ack_gate(ack_sem_ref, hop: int, value: int = 1):
    """Slot-reuse gate of the RX-release protocol: before writing a
    double-buffered comm slot at ring hop ``hop`` (1-based), wait for the
    consumer's ack.  Hops 1 and 2 write fresh slots and pass ungated;
    hop h >= 3 reuses hop h-2's slot and must absorb ``value`` signals
    (one per DMA the consumer drained)."""
    if hop > 2:
        pltpu.semaphore_wait(ack_sem_ref, value)


def ack_release(ack_sem_ref, hop: int, total_hops: int, upstream, value: int = 1):
    """Release half of the protocol: after hop ``hop``'s slot is fully
    consumed — folded/copied *and* any forwarding DMA reading it has
    drained — signal the upstream sender that the slot is free.  Only
    emitted while a future hop (hop+2 <= total_hops) will absorb it, so
    all semaphores drain to zero by kernel end."""
    if hop + 2 <= total_hops:
        pltpu.semaphore_signal(
            ack_sem_ref, inc=value, device_id=upstream,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
