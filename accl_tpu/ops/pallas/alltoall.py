"""All-to-all as a Pallas kernel: direct one-sided writes, no ring.

The reference's ``all_to_all`` is a fused flat tree: every rank copies its
local block, sends buffer addresses to all peers, and serves incoming
address requests out of order
(/root/reference/kernels/cclo/fw/sw_apps/ccl_offload_control/src/
ccl_offload_control.c:2123-2218 — the rendezvous path's one-sided writes).
On TPU the address handshake is unnecessary — SPMD symmetry means every
rank already knows where its block lands — so the kernel is pure payload:
P-1 remote DMAs, each writing block ``p`` of my operand straight into slot
``me`` of rank ``p``'s output, all in flight simultaneously.  This is the
transpose primitive under all-to-all sequence parallelism (Ulysses-style
attention, ``models.ulysses_attention``).
"""

from __future__ import annotations

import jax

from ...compat import install as _compat_install

_compat_install()  # legacy-jax shims (shard_map kwargs, lax.axis_size)
import numpy as np
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import (
    LANES,
    InterpretArg,
    default_interpret,
    require_mosaic_dtypes,
    sublanes_for,
)


def _kernel(axis_name: str, size: int):
    def kernel(x_ref, o_ref, send_sem, recv_sem):
        me = lax.axis_index(axis_name)
        B = x_ref.shape[0] // size

        # ALL peers' output buffers must exist before one-sided writes
        # land — unlike the ring kernels (which only touch neighbors) this
        # writes to every rank, so the barrier is global: signal every
        # peer, wait for every peer
        bar = pltpu.get_barrier_semaphore()
        for d in range(1, size):
            pltpu.semaphore_signal(
                bar, inc=1, device_id=jnp.mod(me + d, size),
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
        pltpu.semaphore_wait(bar, size - 1)

        # local block moves locally
        o_ref[pl.ds(me * B, B), :] = x_ref[pl.ds(me * B, B), :]

        # launch every remote write before waiting any (the flat tree's
        # out-of-order serves: all transfers in flight at once)
        rdmas = []
        for d in range(1, size):
            dst = jnp.mod(me + d, size)
            rdma = pltpu.make_async_remote_copy(
                src_ref=x_ref.at[pl.ds(dst * B, B), :],
                dst_ref=o_ref.at[pl.ds(me * B, B), :],
                send_sem=send_sem.at[d - 1],
                recv_sem=recv_sem.at[d - 1],
                device_id=dst,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()
            rdmas.append(rdma)
        for rdma in rdmas:
            # acclint: allow[unbounded-wait] Mosaic-traced DMA semaphore
            # wait: Pallas remote copies have no timeout form; the host
            # watchdog bounds the whole program
            rdma.wait()

    return kernel


def alltoall(
    x: jax.Array,
    axis_name: str,
    *,
    collective_id: int = 3,
    interpret: InterpretArg = None,
) -> jax.Array:
    """Block transpose across the axis: rank r's output block p is rank
    p's input block r (ref ``ACCL::alltoall``).  ``x``'s leading dim must
    be divisible by the axis size; blocks are padded to lane tiles
    internally per block.

    Note the destination-slot symmetry: my block ``dst`` lands in slot
    ``me`` on ``dst`` — every rank runs the identical program, so each of
    my P-1 slots is written by exactly one peer (recv semaphores indexed
    by ring distance make the accounting static).
    """
    n = x.shape[0]
    size = lax.axis_size(axis_name)
    if n % size:
        raise ValueError(f"leading dim {n} not divisible by axis size {size}")
    if size == 1:
        return x
    interp = default_interpret(interpret)
    require_mosaic_dtypes(interp, "alltoall", x.dtype)
    per_block = n // size
    rest = int(np.prod(x.shape[1:])) if x.ndim > 1 else 1

    # pack each block to (rows, LANES) so per-block DMAs are tile-aligned
    flat = x.reshape(size, per_block * rest)
    m = flat.shape[1]
    sub = sublanes_for(x.dtype)
    rows = max(-(-m // LANES), 1)
    rows = -(-rows // sub) * sub
    pad = rows * LANES - m
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.zeros((size, pad), x.dtype)], axis=1
        )
    packed = flat.reshape(size * rows, LANES)

    out = pl.pallas_call(
        _kernel(axis_name, size),
        out_shape=jax.ShapeDtypeStruct((size * rows, LANES), x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((size - 1,)),
            pltpu.SemaphoreType.DMA((size - 1,)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=interp,
    )(packed)
    return (
        out.reshape(size, rows * LANES)[:, :m].reshape(x.shape)
    )
