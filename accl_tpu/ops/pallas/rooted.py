"""Rooted collectives as Pallas TPU kernels: bcast, reduce, gather, scatter.

Role models: the firmware's rooted algorithms — ``broadcast``
(ccl_offload_control.c:796-988), ``scatter`` (c:992-1123), ``gather`` ring
relay (c:1205-1293), ``reduce`` eager ring pipeline of fused
recv-reduce-send (c:1730-1743).

TPU-first shape choice: the reference's *flat trees* assume an
any-to-any Ethernet fabric; ICI is a neighbor-connected ring/torus, where
a "flat" root fan-out would serialize on the root's two links anyway.  The
hardware-native forms are therefore **ring relays** — exactly the shapes
the reference uses on its *eager* paths — pipelined over ``num_segments``
with the same slot-ack flow control as the ring allreduce kernel (the
RX-buffer release protocol).  Every kernel is uniform SPMD: all ranks run
identical communication structure each hop (sends ungated, folds/stores
predicated on data, never on comm), which keeps the flow control
deadlock-free by construction.

All entry points run inside ``shard_map`` over a 1-D mesh axis whose order
matches the devices' ICI ring; off-TPU they execute under the Pallas TPU
interpreter like the rest of the kernel tier.
"""

from __future__ import annotations

import jax

from ...compat import install as _compat_install

_compat_install()  # legacy-jax shims (shard_map kwargs, lax.axis_size)
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...constants import ReduceFunction
from ._common import (
    LANES,
    InterpretArg,
    neighbor_barrier,
    pack_lanes,
    sublanes_for,
)
# _call carries the shared f16/Mosaic rejection guard — one funnel for
# every remote-DMA collective entry point in ring.py and here
from .ring import _OPS, _call, _hop, _neighbors, _release, ring_allgather


def _relay_scratch(num_segments, seg_rows, dtype):
    return [
        pltpu.VMEM((num_segments, seg_rows, LANES), dtype),  # carry/acc
        pltpu.VMEM((2, num_segments, seg_rows, LANES), dtype),  # comm slots
        pltpu.SemaphoreType.DMA((2, num_segments)),  # send
        pltpu.SemaphoreType.DMA((2, num_segments)),  # recv
        pltpu.SemaphoreType.REGULAR((2, num_segments)),  # slot acks
    ]


def _bcast_kernel(axis_name, size, root, num_segments):
    """P-1 relay hops of the full payload around the ring.  Every rank
    forwards its carry each hop; a rank at distance d from the root adopts
    the incoming payload while hop <= d, after which its carry IS the
    root's data and it keeps relaying it downstream."""
    total_hops = size - 1

    def kernel(x_ref, o_ref, carry, comm, send_sem, recv_sem, ack_sem):
        me, nxt, prv = _neighbors(axis_name, size)
        dist = jnp.mod(me - root, size)
        S = num_segments
        segB = comm.shape[2]

        neighbor_barrier(nxt, prv)
        for j in range(S):
            carry[j] = x_ref[pl.ds(j * segB, segB), :]
        for t in range(1, size):
            slot = t % 2
            rdmas = [
                _hop(comm.at[slot, j], carry.at[j],
                     send_sem.at[slot, j], recv_sem.at[slot, j],
                     ack_sem.at[slot, j], nxt, t)
                for j in range(S)
            ]
            adopt = t <= dist
            for j in range(S):
                rdmas[j].wait_recv()
                rdmas[j].wait_send()
                carry[j] = jnp.where(adopt, comm[slot, j], carry[j])
                _release(ack_sem.at[slot, j], prv, t, total_hops)
        for j in range(S):
            o_ref[pl.ds(j * segB, segB), :] = carry[j]

    return kernel


def _reduce_kernel(axis_name, size, root, num_segments, op):
    """The reference's eager reduce pipeline (c:1730-1743): partials flow
    from the farthest rank toward the root, each relay folding its own
    contribution.  Uniform form: every rank sends its accumulator toward
    the root every hop; rank at root-distance ``rel`` folds exactly at hop
    ``P-1-rel``, when the incoming accumulator has become final."""
    total_hops = size - 1

    def kernel(x_ref, o_ref, acc, comm, send_sem, recv_sem, ack_sem):
        me, nxt, prv = _neighbors(axis_name, size)
        rel = jnp.mod(me - root, size)
        S = num_segments
        segB = comm.shape[2]

        neighbor_barrier(nxt, prv)
        for j in range(S):
            acc[j] = x_ref[pl.ds(j * segB, segB), :]
        for t in range(1, size):
            slot = t % 2
            # partials travel toward the root: send to prv, receive from nxt
            rdmas = [
                _hop(comm.at[slot, j], acc.at[j],
                     send_sem.at[slot, j], recv_sem.at[slot, j],
                     ack_sem.at[slot, j], prv, t)
                for j in range(S)
            ]
            fold = t == (size - 1) - rel
            for j in range(S):
                rdmas[j].wait_recv()
                rdmas[j].wait_send()
                acc[j] = jnp.where(fold, op(acc[j], comm[slot, j]), acc[j])
                _release(ack_sem.at[slot, j], nxt, t, total_hops)
        for j in range(S):
            o_ref[pl.ds(j * segB, segB), :] = acc[j]

    return kernel


def _scatter_kernel(axis_name, size, root, num_segments):
    """Farthest-first pipeline (the ring form of the root fan-out,
    c:1080-1122): at hop t the root injects the block destined for
    root-distance P-t; relays forward what they received the hop before;
    every non-root rank's own block arrives exactly at the final hop."""
    total_hops = size - 1

    def kernel(x_ref, o_ref, carry, comm, send_sem, recv_sem, ack_sem):
        me, nxt, prv = _neighbors(axis_name, size)
        rel = jnp.mod(me - root, size)
        is_root = rel == 0
        S = num_segments
        segB = comm.shape[2]
        B = S * segB  # rows per destination block

        neighbor_barrier(nxt, prv)
        for j in range(S):
            zero = x_ref[pl.ds(j * segB, segB), :] * 0
            # root's own block (absolute block id == root, static)
            o_ref[pl.ds(j * segB, segB), :] = jnp.where(
                is_root, x_ref[pl.ds(root * B + j * segB, segB), :], zero
            )
            carry[j] = zero
        for t in range(1, size):
            slot = t % 2
            # the block the root injects this hop: destination distance
            # P-t, absolute rank (root + P - t) % size — static per hop
            inj = (root + size - t) % size
            for j in range(S):
                carry[j] = jnp.where(
                    is_root, x_ref[pl.ds(inj * B + j * segB, segB), :],
                    carry[j],
                )
            rdmas = [
                _hop(comm.at[slot, j], carry.at[j],
                     send_sem.at[slot, j], recv_sem.at[slot, j],
                     ack_sem.at[slot, j], nxt, t)
                for j in range(S)
            ]
            mine = t == size - 1  # own block arrives on the final hop
            for j in range(S):
                rdmas[j].wait_recv()
                rdmas[j].wait_send()
                o_ref[pl.ds(j * segB, segB), :] = jnp.where(
                    jnp.logical_and(mine, jnp.logical_not(is_root)),
                    comm[slot, j],
                    o_ref[pl.ds(j * segB, segB), :],
                )
                carry[j] = comm[slot, j]
                _release(ack_sem.at[slot, j], prv, t, total_hops)

    return kernel


def ring_bcast(
    x: jax.Array,
    axis_name: str,
    root: int = 0,
    num_segments: int = 1,
    *,
    collective_id: int = 0,
    interpret: InterpretArg = None,
) -> jax.Array:
    """Broadcast the root's operand to every rank via ring relay."""
    size = lax.axis_size(axis_name)
    if size == 1:
        return x
    xp, n = pack_lanes(x, min_rows=num_segments * sublanes_for(x.dtype))
    rows = xp.shape[0]
    seg_rows = rows // num_segments
    out = _call(
        _bcast_kernel(axis_name, size, root, num_segments),
        xp, rows, _relay_scratch(num_segments, seg_rows, x.dtype),
        collective_id, interpret,
    )
    return out.reshape(-1)[:n].reshape(x.shape)


def ring_reduce(
    x: jax.Array,
    axis_name: str,
    root: int = 0,
    function: ReduceFunction = ReduceFunction.SUM,
    num_segments: int = 1,
    *,
    collective_id: int = 0,
    interpret: InterpretArg = None,
) -> jax.Array:
    """Reduce to ``root`` via the fused recv-reduce-send ring pipeline;
    the returned array is the full reduction on the root and an
    intermediate partial elsewhere (callers read the root's result, like
    the reference's DummyBuffer non-root recv)."""
    size = lax.axis_size(axis_name)
    if size == 1:
        return x
    op = _OPS[function]
    xp, n = pack_lanes(x, min_rows=num_segments * sublanes_for(x.dtype))
    rows = xp.shape[0]
    seg_rows = rows // num_segments
    out = _call(
        _reduce_kernel(axis_name, size, root, num_segments, op),
        xp, rows, _relay_scratch(num_segments, seg_rows, x.dtype),
        collective_id, interpret,
    )
    return out.reshape(-1)[:n].reshape(x.shape)


def ring_scatter(
    x: jax.Array,
    axis_name: str,
    root: int = 0,
    num_segments: int = 1,
    *,
    collective_id: int = 0,
    interpret: InterpretArg = None,
) -> jax.Array:
    """Scatter the root's ``size`` consecutive blocks: rank of
    root-distance d receives block ``(root+d) % size``.  ``x`` must have
    the same (full) shape on every rank; only the root's values matter."""
    size = lax.axis_size(axis_name)
    if size == 1:
        return x
    flat = x.reshape(-1)
    if flat.shape[0] % size:
        raise ValueError(f"scatter operand {flat.shape[0]} % {size} != 0")
    blk = flat.shape[0] // size
    sub = sublanes_for(x.dtype)
    # blocks must be row-aligned so each destination block is a contiguous
    # row range in the packed operand: pack per block, then concatenate
    per_blk = jnp.stack(
        [
            pack_lanes(flat[i * blk : (i + 1) * blk],
                       min_rows=num_segments * sub)[0]
            for i in range(size)
        ]
    )
    xp = per_blk.reshape(-1, LANES)
    rows = xp.shape[0]
    seg_rows = rows // (size * num_segments)
    out = _call(
        _scatter_kernel(axis_name, size, root, num_segments),
        xp, rows // size, _relay_scratch(num_segments, seg_rows, x.dtype),
        collective_id, interpret,
    )
    return out.reshape(-1)[:blk]


def ring_gather(
    x: jax.Array,
    axis_name: str,
    root: int = 0,
    num_segments: int = 1,
    *,
    collective_id: int = 0,
    interpret: InterpretArg = None,
) -> jax.Array:
    """Gather every rank's block to the root.  On a ring fabric this is
    the store-and-relay of the reference's eager gather (c:1205-1293),
    whose wire traffic equals the allgather relay — so it reuses that
    kernel; non-root outputs are simply unused (the DummyBuffer role).
    ``root`` is accepted for signature parity."""
    del root  # every rank materializes the gather; the root's copy is read
    return ring_allgather(
        x, axis_name, num_segments,
        collective_id=collective_id, interpret=interpret,
    )
