"""The device-resident command ring: the persistent sequencer lowerings.

Role model: the reference's CCLO firmware run loop — the host enqueues
fixed-width commands into a hardware FIFO and the offload kernel's own
infinite loop decodes and executes whole collectives with no host in
the data path (``ccl_offload_control.c`` run loop + ``dma_mover``).
The TPU analog built here is genuinely *multi-window persistent*: one
sequencer **run** is ONE long-running device program that drains up to
``run_windows`` refill windows from the host-visible mailbox
(:mod:`accl_tpu.cmdring`) before returning — consecutive warm windows
execute with ZERO program re-dispatches, and the doorbell is a mailbox
write, not a launch.

Split of responsibilities:

* host half (slot codec + mailbox protocol): ``accl_tpu/cmdring.py``
  (numpy-only — re-exported here for the established import surface);
* device half (this module): the decode loop, twice lowered;
* engine half (sessions, refills, fallbacks): ``backends/xla/cmdring.py``.

ONE decode loop, two lowerings — both read the same
:data:`accl_tpu.constants.CMDRING_FIELDS` slot words and share the
data-driven per-slot epilogue (:func:`slot_epilogue`), which covers the
FULL opcode space: ALLREDUCE, BCAST, REDUCE_SCATTER, ALLGATHER,
ALLTOALL, BARRIER and SEND/RECV pair slots.  Opcode, reduce function,
root and peer are decoded ON DEVICE from the slot words — a warm run
never recompiles on op/function/root churn; only the window's payload
*shape signature* (per-slot widths + wire-cast dtypes) keys the
program cache, because output geometry is a compile-time fact.

* ``"xla"`` — the persistent session program: a ``scan``-bounded run
  loop whose every step pulls the next window from the mailbox (ordered
  ``io_callback``), executes every slot (``lax.all_gather`` wire move +
  the shared epilogue) and pushes the per-slot ``(seqn, retcode)``
  status words and results back.  This is the emulator/CI tier —
  provable on the virtual CPU mesh, with the mailbox decision protocol
  guaranteeing every rank sees the identical window schedule.
* ``"pallas"`` — the mega-window kernel: one Mosaic program whose
  ``fori``-shaped window×slot loop drains a backlog of refill windows
  staged into the slot mailbox region at the doorbell; per slot the
  gather hops are Mosaic remote DMAs over ICI driven by the ring
  kernels' store-and-relay machine (``ring.relay_allgather_hops``; the
  two-rank form composes ``put.remote_block_put``), with a neighbor
  barrier between slots gating comm-slot reuse.  f16 windows ride a
  f32 compute view installed around the kernel (Mosaic has no f16);
  per-slot wire casts run as rounding lanes inside the decode loop.

Payloads ride the gather at the window's uniform tile-aligned height;
results are trimmed by host-side adoption (pads are never observed).
Oversized payloads never get here — the engine falls back to host
dispatch above ``CMDRING_MAX_PAYLOAD_BYTES``.
"""

from __future__ import annotations

import math
from functools import lru_cache
from typing import Optional, Sequence

import numpy as np

import jax

from ...compat import install as _compat_install

_compat_install()  # legacy-jax shims (shard_map kwargs, lax.axis_size)
import jax.numpy as jnp
from jax import lax
from jax.experimental import io_callback
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# the host half re-exported: tests/tools import the codec from here
from ...cmdring import (  # noqa: F401  (re-export surface)
    SequencerMailbox,
    WindowShape,
    decode_fparam,
    decode_slot,
    encode_fparam,
    encode_slot,
    encode_window,
    fused_slot_eligible,
    mailbox_for,
    register_mailbox,
    ring_widths,
    unregister_mailbox,
)
from ...constants import (
    CMDRING_FIELDS,
    CMDRING_FPARAM_ONE,
    CMDRING_SLOT_WORDS,
    CMDRING_ST_BAD_OP,
    CMDRING_ST_OK,
    CmdOpcode,
)
from ._common import (
    LANES,
    InterpretArg,
    default_interpret,
    require_mosaic_dtypes,
    sublanes_for,
)
from .attention import attn_hop_partial
from .put import remote_block_put
from .ring import _neighbors, _ring_barrier, hop_source, relay_allgather_hops
from ... import wire as wirecodec
from .. import wire as devwire

__all__ = [
    "decode_fparam",
    "decode_slot",
    "encode_fparam",
    "encode_slot",
    "encode_window",
    "fused_slot_eligible",
    "run_session",
    "run_windows",
    "session_program",
    "slot_epilogue",
    "status_words",
]

_F = CMDRING_FIELDS  # the one layout table (constants.py)


# ---------------------------------------------------------------------------
# the shared decode loop pieces (both lowerings)
# ---------------------------------------------------------------------------


def _reduce_chain(blocks, fn):
    """Data-driven fold over the gathered per-rank blocks: SUM and MAX
    both computed as static chains, the ReduceFunction scalar (read
    from the slot words ON DEVICE) selects.  Chain order is rank order
    on every rank — the determinism the replay test pins."""
    acc_sum = blocks[0]
    acc_max = blocks[0]
    for b in blocks[1:]:
        acc_sum = acc_sum + b
        acc_max = jnp.maximum(acc_max, b)
    from ...constants import ReduceFunction

    return jnp.where(fn == int(ReduceFunction.MAX), acc_max, acc_sum)


def _root_select(blocks, root):
    """Static-indexed select chain of the ``root``-th block (no dynamic
    gather: both the VPU and the CPU tier lower where-chains)."""
    out = blocks[0]
    for r in range(1, len(blocks)):
        out = jnp.where(root == r, blocks[r], out)
    return out


def _fparam_scale(fparam, dtype):
    """The fused epilogue's scalar, decoded ON DEVICE from the slot's
    Q16.16 ``fparam`` word (int-to-float multiply by the exact
    power-of-two reciprocal — no float bit-pattern punning through the
    int32 slot plane; both lowerings decode identically)."""
    if fparam is None:
        fparam = 0
    fp = jnp.asarray(fparam, jnp.int32).astype(jnp.float32)
    return (fp * (1.0 / CMDRING_FPARAM_ONE)).astype(dtype)


def _attn_hop_result(blocks, own, me, peer, out_lead, fp):
    """The FUSED_ATTN_HOP candidate: the slot's ``peer`` word is the hop
    OFFSET (SPMD-uniform), each rank derives its source rank on device
    and folds the visiting kv block against the resident q block riding
    the operand tail."""
    size = len(blocks)
    src = hop_source(me, peer, size)
    visiting = _root_select(blocks, src)
    return attn_hop_partial(
        own[out_lead:2 * out_lead], visiting[:out_lead], fp
    )


def slot_epilogue(blocks, own, me, op, fn, root, peer, out_lead,
                  chunk: Optional[int] = None, fparam=None):
    """ONE per-slot decode epilogue for the full opcode space, shared by
    both lowerings.  ``blocks`` is the gathered per-rank block list
    (static length = world size), ``own`` this rank's (pass-through)
    operand, and ``op``/``fn``/``root``/``peer``/``fparam`` int32
    scalars read from the slot words ON DEVICE.  ``out_lead`` is the
    slot's static result height along the leading axis; ``chunk`` the
    per-rank sub-block height for the P-wide ops (``in_lead // size`` —
    element-granular on the flat XLA form, row-granular on the packed
    Pallas form).

    Output GEOMETRY is compile-time (it shapes the program), so the
    width class picks the candidate set and the opcode selects within
    the class as data:

    * ``out == in * size``      → ALLGATHER (the gathered stack);
    * ``in == out * (size+1)``  → FUSED_APPLY (optimizer apply-on-
      arrival: the param chunk riding the operand tail minus
      ``fparam`` times this rank's reduced gradient chunk — the apply
      happens during the gather, not after it);
    * ``in == out * size``      → REDUCE_SCATTER / FUSED_MATMUL_RS
      (fold, take my chunk; the fused form scales by ``fparam`` — the
      vadd_put discipline) / FUSED_ATTN_HOP at size 2 (where the hop
      class coincides);
    * ``in == out * 2``, size>2 → FUSED_ATTN_HOP (kv block relays one
      hop, the epilogue emits the scaled partial against the resident
      q block on the operand tail);
    * ``out == in``             → ALLREDUCE / BCAST / ALLTOALL /
      BARRIER / SEND / RECV / NOP selected by the opcode word: the
      fold, the root block, the transpose-of-chunks, the pass-through
      token, the pair move (``me == peer`` adopts the src block), or
      ``own``.
    """
    size = len(blocks)
    in_lead = own.shape[0]
    if size == 1:
        return own[:out_lead] if out_lead <= in_lead else own
    if out_lead == in_lead * size:
        # ALLGATHER class: the gathered stack is the result — opcode
        # still guards as data, so a mis-encoded slot yields its own
        # operand tiled instead of silently gathering
        cat = jnp.concatenate(blocks, axis=0)
        return jnp.where(
            op == int(CmdOpcode.ALLGATHER),
            cat,
            jnp.concatenate([own] * size, axis=0),
        )
    reduced = _reduce_chain(blocks, fn)
    fp = _fparam_scale(fparam, own.dtype)
    if in_lead == out_lead * (size + 1):
        # FUSED_APPLY class: gradients in allreduce layout with this
        # rank's param chunk riding the operand tail.  Fold the
        # gathered gradients, take my chunk, apply p - lr*g — the
        # optimizer step runs per received chunk during the gather.
        # Opcode guards as data: a mis-encoded slot passes its own
        # leading chunk through untouched.
        grad = lax.dynamic_slice_in_dim(reduced, me * out_lead, out_lead)
        mine = own[size * out_lead:(size + 1) * out_lead]
        return jnp.where(
            op == int(CmdOpcode.FUSED_APPLY),
            mine - fp * grad,
            own[:out_lead],
        )
    if in_lead == out_lead * size:
        # REDUCE_SCATTER class: fold everything, keep my chunk (opcode
        # guard as above — a mis-encoded slot keeps its own chunk).
        # FUSED_MATMUL_RS shares the geometry and scales the chunk by
        # fparam (the GEMM-partial epilogue feeding the relay); at
        # size 2 the attn-hop class coincides (2*out == size*out) and
        # the opcode word selects it here.
        mine = lax.dynamic_slice_in_dim(reduced, me * out_lead, out_lead)
        res = jnp.where(
            op == int(CmdOpcode.REDUCE_SCATTER),
            mine,
            lax.dynamic_slice_in_dim(own, me * out_lead, out_lead),
        )
        res = jnp.where(
            op == int(CmdOpcode.FUSED_MATMUL_RS), fp * mine, res
        )
        if size == 2:
            res = jnp.where(
                op == int(CmdOpcode.FUSED_ATTN_HOP),
                _attn_hop_result(blocks, own, me, peer, out_lead, fp),
                res,
            )
        return res
    if in_lead == out_lead * 2:
        # FUSED_ATTN_HOP class (size > 2): kv ‖ q operand rows — the
        # relay moves the kv block one hop, the epilogue contracts it
        # against the resident q block
        return jnp.where(
            op == int(CmdOpcode.FUSED_ATTN_HOP),
            _attn_hop_result(blocks, own, me, peer, out_lead, fp),
            own[:out_lead],
        )
    rooted = _root_select(blocks, root)
    res = jnp.where(op == int(CmdOpcode.ALLREDUCE), reduced, own)
    res = jnp.where(op == int(CmdOpcode.BCAST), rooted, res)
    # BARRIER: the gather that fed `blocks` IS the sync; the result is
    # the pass-through token
    res = jnp.where(op == int(CmdOpcode.BARRIER), own, res)
    # SEND/RECV pair slot: root=src, peer=dst — the destination adopts
    # the source block, everyone else passes through (their result is
    # never written back; writers = {dst} at adoption)
    pair = jnp.where(me == peer, rooted, own)
    res = jnp.where(
        (op == int(CmdOpcode.SEND)) | (op == int(CmdOpcode.RECV)),
        pair, res,
    )
    if chunk is not None and chunk * size == in_lead and chunk > 0:
        a2a = jnp.concatenate(
            [
                lax.dynamic_slice_in_dim(blocks[j], me * chunk, chunk)
                for j in range(size)
            ],
            axis=0,
        )
        res = jnp.where(op == int(CmdOpcode.ALLTOALL), a2a, res)
    return res


#: the opcode range the status check accepts — derived from the enum,
#: never a hardcoded member, so growing CmdOpcode (with the acclint
#: cross-file check enforcing the wiring) never stamps BAD_OP on a
#: fully implemented opcode
_MAX_OPCODE = max(int(o) for o in CmdOpcode)


def status_words(slots):
    """Per-slot ``(seqn, retcode)`` status words, computed ON DEVICE
    from the slot data by the same program that executes the window —
    the completion words the host drainer reads from the status FIFO.
    Every CmdOpcode is implemented; out-of-range opcodes stamp
    ``CMDRING_ST_BAD_OP``."""
    op = slots[:, _F["opcode"]]
    ok = (op >= 0) & (op <= _MAX_OPCODE)
    ret = jnp.where(ok, CMDRING_ST_OK, CMDRING_ST_BAD_OP).astype(jnp.int32)
    return jnp.stack([slots[:, _F["seqn"]], ret], axis=1)


def _decode_slot_xla(slots, i, own, me, size, shape: WindowShape):
    """One slot of the flat (element-granular) XLA decode loop: the
    wire-cast rounding lane, the ``lax.all_gather`` wire move (int8
    lanes additionally gather their per-segment scale sidecar — the
    honest wire-byte accounting), and the shared epilogue.  The SR seed
    rides the slot's ``flags`` word as DATA (rank-mixed on device), so
    seed churn never recompiles a warm window."""
    wire = shape.wires[i]
    x = own
    if wire is not None:
        # the compressed lane lowered into the decode loop: every
        # contribution rounds through the wire dtype exactly like the
        # compressed_allreduce program (single rounding, on device).
        # ONE lane helper covers every registered wire dtype on both
        # lowerings (acclint cross-checks this module for it).
        from ...constants import numpy_to_dtype

        seed = devwire.rank_seed(
            slots[i, _F["flags"]].astype(jnp.uint32), me
        )
        if wirecodec.is_scaled(numpy_to_dtype(np.dtype(wire))):
            # scaled lane: the wire moves int8 values + fp32 scales;
            # contributions dequantize per source rank before the fold
            q, scales = devwire.quantize_int8(x, seed)
            gq = lax.all_gather(q, _axis_name())
            gs = lax.all_gather(scales, _axis_name())
            in_w = shape.in_ws[i]
            blocks = [
                devwire.dequantize_int8(
                    gq[r], gs[r], in_w, out_dtype=own.dtype
                )
                for r in range(size)
            ]
            chunk = in_w // size if size and in_w % size == 0 else None
            return slot_epilogue(
                blocks, own, me,
                slots[i, _F["opcode"]],
                slots[i, _F["function"]],
                slots[i, _F["root"]],
                slots[i, _F["peer"]],
                shape.out_ws[i],
                chunk=chunk,
                fparam=slots[i, _F["fparam"]],
            )
        x = devwire._cast_lane(x, jnp.dtype(wire), seed)
    g = lax.all_gather(x, _axis_name())
    blocks = [g[r].astype(own.dtype) for r in range(size)]
    in_w = shape.in_ws[i]
    chunk = in_w // size if size and in_w % size == 0 else None
    return slot_epilogue(
        blocks, own, me,
        slots[i, _F["opcode"]],
        slots[i, _F["function"]],
        slots[i, _F["root"]],
        slots[i, _F["peer"]],
        shape.out_ws[i],
        chunk=chunk,
        fparam=slots[i, _F["fparam"]],
    )




def _axis_name():
    from ..driver import AXIS

    return AXIS


# ---------------------------------------------------------------------------
# the persistent session program (xla lowering): one dispatch, N windows
# ---------------------------------------------------------------------------


def _pull_host_fn(shape: WindowShape, size: int):
    """Host target of the run loop's pull callback.  Resolves the
    mailbox through the registry BY ID (an operand, not a closure) so
    the compiled program is reusable across runs; a missing mailbox —
    a torn-down session whose run is still draining — degrades to HALT
    payloads instead of wedging the program."""

    def pull(mid, rank):
        mbox = mailbox_for(int(mid))
        if mbox is None:
            return (
                np.int32(0),
                np.zeros((shape.depth, CMDRING_SLOT_WORDS), np.int32),
                *[np.zeros((w,), shape.npdt) for w in shape.in_ws],
            )
        try:
            live, slots, payload = mbox.pull(int(rank))
        except Exception:  # never wedge the device program
            import traceback

            traceback.print_exc()
            return (
                np.int32(0),
                np.zeros((shape.depth, CMDRING_SLOT_WORDS), np.int32),
                *[np.zeros((w,), shape.npdt) for w in shape.in_ws],
            )
        return (live, slots, *payload)

    return pull


def _push_host_fn():
    def push(mid, rank, live, status, *outs):
        mbox = mailbox_for(int(mid))
        if mbox is not None:
            try:
                mbox.push(int(rank), int(live), status, list(outs))
            except Exception:
                import traceback

                traceback.print_exc()
        return np.int32(0)

    return push


@lru_cache(maxsize=64)
def _session_program(mesh_id: int, shape_key: tuple, nwin: int):
    """The compiled persistent run: ``(anchor) -> anchor`` where the
    anchor's per-rank shard carries the mailbox id.  The run loop is a
    genuine ``while_loop`` — pull the next window, and while it is
    live: decode/execute every slot, push status + results, pull
    again.  A HALT decision exits the loop IMMEDIATELY (no tail steps,
    no zero-payload gathers — the parked sequencer costs nothing), so
    a run's lifetime is exactly its windows plus one cheap halt pull;
    ``nwin`` bounds the loop as a belt on top of the mailbox's window
    budget.  Only the window SHAPE and the bound key this cache —
    mailbox identity is data, so every run of a shape reuses one
    executable."""
    from ..driver import _MESHES, AXIS, _smap

    mesh = _MESHES[mesh_id]
    size = mesh.devices.size
    depth, in_ws, out_ws, wires, npdt_name = shape_key
    shape = WindowShape(depth, in_ws, out_ws, wires, npdt_name)
    npdt = shape.npdt
    pull = _pull_host_fn(shape, size)
    push = _push_host_fn()
    pull_shapes = (
        jax.ShapeDtypeStruct((), jnp.int32),
        jax.ShapeDtypeStruct((depth, CMDRING_SLOT_WORDS), jnp.int32),
        *[jax.ShapeDtypeStruct((w,), npdt) for w in in_ws],
    )

    def body(anchor):
        mid = anchor[0]
        me = lax.axis_index(AXIS)

        def do_pull():
            return io_callback(pull, pull_shapes, mid, me, ordered=True)

        def cond(carry):
            return (carry[0] > 0) & (carry[1] < nwin)

        def step(carry):
            _live, n, slots, *payload = carry
            status = status_words(slots)
            outs = [
                _decode_slot_xla(slots, i, payload[i], me, size, shape)
                for i in range(depth)
            ]
            io_callback(
                push, jax.ShapeDtypeStruct((), jnp.int32),
                mid, me, jnp.int32(1), status, *outs, ordered=True,
            )
            nlive, nslots, *npayload = do_pull()
            return (nlive, n + 1, nslots, *npayload)

        live0, slots0, *payload0 = do_pull()
        lax.while_loop(
            cond, step, (live0, jnp.int32(0), slots0, *payload0)
        )
        return anchor

    spec = jax.sharding.PartitionSpec(AXIS)
    return _smap(mesh, body, (spec,), spec)


def session_program(mesh, shape: WindowShape, nwin: int):
    """Prepared persistent-run handle (the engine dispatches it once per
    run; every refill after that is a mailbox post)."""
    from ..driver import _mesh_key

    return _session_program(_mesh_key(mesh), shape.key(), int(nwin))


def run_session(mesh, shape: WindowShape, mbox_id: int, nwin: int):
    """Dispatch one persistent sequencer run: launches the run-loop
    program armed with ``mbox_id`` and returns the output handle (held
    by the engine's run record; completion flows through the mailbox's
    push path, never through blocking on this handle)."""
    from jax.sharding import NamedSharding, PartitionSpec

    from ..driver import AXIS

    prog = session_program(mesh, shape, nwin)
    size = mesh.devices.size
    anchor = jax.device_put(
        np.full((size,), int(mbox_id), np.int32),
        NamedSharding(mesh, PartitionSpec(AXIS)),
    )
    return prog(anchor)


# ---------------------------------------------------------------------------
# the Pallas mega-window kernel (chip tier): one Mosaic program, a
# backlog of windows
# ---------------------------------------------------------------------------


def _sequencer_kernel(axis_name: str, size: int, nwin: int, depth: int,
                      rows: int, out_rows: Sequence[int],
                      chunk_rows: Optional[int]):
    """The mega-window sequencer as ONE Mosaic program: the kernel's
    window × slot loop — not host dispatch — sequences ``nwin * depth``
    collectives.  ``rows`` is the uniform tile-aligned per-slot payload
    height; slot ``(w, i)`` owns ``x_ref[(w*depth+i)*rows : ...]``.  Per
    slot: ring-allgather the block via the store-and-relay remote-DMA
    machine (the two-rank ring degenerates to one
    ``put.remote_block_put`` exchange), then run the shared data-driven
    epilogue on the VPU.  A neighbor barrier separates slots so slot
    ``k+1``'s first hop can never overwrite a comm slot its consumer is
    still folding.  ``chunk_rows`` (= ``rows // size``) gives the
    P-wide ops their row-aligned per-rank sub-blocks."""

    def kernel(slots_ref, x_ref, o_ref, gathered, carry, comm, send_sem,
               recv_sem, ack_sem):
        me, nxt, prv = _neighbors(axis_name, size)
        out_off = 0
        for w in range(nwin):
            for i in range(depth):
                k = w * depth + i
                _ring_barrier(nxt, prv)  # doorbell + slot-reuse gate
                block = x_ref[pl.ds(k * rows, rows), :]
                gathered[pl.ds(me * rows, rows), :] = block
                if size == 2:
                    # two-rank gather IS one neighbor put (the put.py
                    # primitive): my block lands in the peer's comm slot
                    carry[0] = block
                    remote_block_put(
                        carry.at[0],
                        comm.at[0, 0],
                        send_sem.at[0, 0],
                        recv_sem.at[0, 0],
                        nxt,
                    )
                    gathered[pl.ds(prv * rows, rows), :] = comm[0, 0]
                elif size > 2:
                    carry[0] = block

                    def place(origin, _j, data):
                        gathered[pl.ds(origin * rows, rows), :] = data

                    relay_allgather_hops(
                        place, carry, comm, send_sem, recv_sem, ack_sem,
                        me, nxt, prv, size,
                    )
                # decode the slot words from SMEM (scalar reads) and run
                # the SAME epilogue the xla lowering uses
                op = slots_ref[k, _F["opcode"]]
                fn = slots_ref[k, _F["function"]]
                root = slots_ref[k, _F["root"]]
                peer = slots_ref[k, _F["peer"]]
                fparam = slots_ref[k, _F["fparam"]]
                blocks = [
                    gathered[pl.ds(r * rows, rows), :] for r in range(size)
                ]
                o_rows = out_rows[i]
                res = slot_epilogue(
                    blocks, block, me, op, fn, root, peer, o_rows,
                    chunk=chunk_rows,
                    fparam=fparam,
                )
                o_ref[pl.ds(out_off, o_rows), :] = res
                out_off += o_rows

    return kernel


def _pack_rows(x, rows: int, chunks: int, dtype):
    """Pack a flat operand into ``(rows, LANES)``: flat for the 1-wide
    ops, per-rank-chunk row-aligned for the P-wide ops so the epilogue's
    row slicing lands on chunk boundaries."""
    if chunks <= 1:
        w = x.shape[0]
        pad = rows * LANES - w
        if pad:
            x = jnp.concatenate([x, jnp.zeros((pad,), dtype)])
        return x.reshape(rows, LANES)
    crows = rows // chunks
    n = x.shape[0] // chunks
    parts = []
    for c in range(chunks):
        seg = x[c * n:(c + 1) * n]
        pad = crows * LANES - n
        if pad:
            seg = jnp.concatenate([seg, jnp.zeros((pad,), dtype)])
        parts.append(seg.reshape(crows, LANES))
    return jnp.concatenate(parts, axis=0)


def _unpack_rows(y, w: int, chunks: int):
    """Inverse of :func:`_pack_rows` for a slot's result region."""
    if chunks <= 1:
        return y.reshape(-1)[:w]
    crows = y.shape[0] // chunks
    n = w // chunks
    return jnp.concatenate(
        [
            y[c * crows:(c + 1) * crows].reshape(-1)[:n]
            for c in range(chunks)
        ]
    )


def _pallas_windows(slots, xs, axis_name, size, nwin, depth,
                    shape: WindowShape, me=None,
                    interpret: InterpretArg = None):
    """Trace a backlog of ``nwin`` windows through one ``pallas_call``.
    Per-slot operands are packed to one uniform tile-aligned height
    inside the traced body (zero extra dispatch — this all runs in the
    SAME program); f16 windows ride a f32 compute view around the
    kernel (Mosaic has no f16) and per-slot wire casts run as rounding
    lanes before packing (the SAME shared lane helper the xla lowering
    decodes with — fp8/int8 included, seeds from the slot ``flags``
    words) — both 'inside the decode loop' at the program level, with
    no extra host interaction."""
    npdt = shape.npdt
    f16_view = np.dtype(npdt) == np.float16
    compute = jnp.float32 if f16_view else npdt
    interp = default_interpret(interpret)
    require_mosaic_dtypes(interp, "command-ring sequencer", compute)
    sub = sublanes_for(compute)
    # per-slot chunking decided ONCE and used by pack, kernel slicing
    # AND unpack — a pack/unpack mismatch would read padding as payload.
    # Fused slots are classified by their width RELATIONS first (the
    # same relations the epilogue branches on): an APPLY operand packs
    # as size+1 chunks (grads ‖ param tail), an attn-hop operand as 2
    # (kv ‖ q); everything else keeps the plain rule.
    def _chunks_of(in_w: int, ow: int) -> int:
        if size > 1 and in_w == ow * (size + 1):
            return size + 1
        if size > 1 and in_w == ow * size:
            return size
        if size > 1 and in_w == 2 * ow and ow < in_w:
            return 2
        return size if in_w % size == 0 and in_w >= size else 1

    slot_chunks = [
        _chunks_of(shape.in_ws[i], shape.out_ws[i]) for i in range(depth)
    ]
    # uniform slot height: rows = pc * L with pc the sublane-rounded
    # max per-chunk height and L the lcm of every chunk divisor in the
    # window (plus size, so plain P-wide slicing lands on row
    # boundaries and rows // c stays sublane-aligned for every class)
    pc = max(
        -(-max(
            shape.in_ws[i] // max(slot_chunks[i], 1)
            if slot_chunks[i] > 1 else shape.in_ws[i]
            for i in range(depth)
        ) // LANES), 1)
    pc = -(-pc // sub) * sub
    lcm = size
    for c in set(slot_chunks):
        if c > 1:
            lcm = lcm * c // math.gcd(lcm, c)
    rows = pc * lcm
    chunk_rows = rows // size  # the P-wide per-rank sub-block height
    out_rows = []
    for i in range(depth):
        ow = shape.out_ws[i]
        in_w = shape.in_ws[i]
        if ow >= in_w * size and size > 1:
            out_rows.append(rows * size)          # allgather class
        elif in_w == ow * (size + 1) and size > 1:
            out_rows.append(rows // (size + 1))   # fused-apply class
        elif in_w == ow * size and size > 1:
            out_rows.append(chunk_rows)           # reduce-scatter class
        elif in_w == 2 * ow and ow < in_w and size > 1:
            out_rows.append(rows // 2)            # attn-hop class
        else:
            out_rows.append(rows)
    packed = []
    for w_idx in range(nwin):
        for i in range(depth):
            x = xs[w_idx][i].astype(compute)
            wire = shape.wires[i]
            if wire is not None and np.dtype(wire) != np.dtype(npdt):
                # wire rounding lane inside the decode loop (the shared
                # per-lane helper: cast lanes + the scaled int8 lane,
                # SR seed from the slot flags word); Mosaic dtypes only
                # INSIDE the kernel — the rounding happens in jnp
                # before packing, so fp8/int8 lanes ride fine while the
                # engine routes f16 wires to the xla lowering
                k = w_idx * depth + i
                seed = devwire.rank_seed(
                    slots[k, _F["flags"]].astype(jnp.uint32),
                    me if me is not None else jnp.uint32(0),
                )
                x = devwire.wire_lane_roundtrip(
                    x, jnp.dtype(wire), seed
                )
            packed.append(_pack_rows(x, rows, slot_chunks[i], compute))
    xp = jnp.concatenate(packed, axis=0)
    total_out = sum(out_rows) * nwin
    scratch = [
        pltpu.VMEM((size * rows, LANES), compute),  # gathered blocks
        pltpu.VMEM((1, rows, LANES), compute),      # relay carry
        pltpu.VMEM((2, 1, rows, LANES), compute),   # comm slots
        pltpu.SemaphoreType.DMA((2, 1)),            # send
        pltpu.SemaphoreType.DMA((2, 1)),            # recv
        pltpu.SemaphoreType.REGULAR((2, 1)),        # slot acks
    ]
    out = pl.pallas_call(
        _sequencer_kernel(
            axis_name, size, nwin, depth, rows, out_rows, chunk_rows
        ),
        out_shape=jax.ShapeDtypeStruct((total_out, LANES), compute),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=scratch,
        compiler_params=_compiler_params(),
        interpret=interp,
    )(slots, xp)
    outs = []
    off = 0
    for w_idx in range(nwin):
        per = []
        for i in range(depth):
            region = out[off:off + out_rows[i]]
            off += out_rows[i]
            ow = shape.out_ws[i]
            if out_rows[i] == rows * size:
                # allgather class: size blocks, each laid out exactly
                # like the (possibly chunk-packed) input block
                in_w = shape.in_ws[i]
                got = jnp.concatenate([
                    _unpack_rows(
                        region[b * rows:(b + 1) * rows], in_w,
                        slot_chunks[i],
                    )
                    for b in range(size)
                ]).astype(npdt)
            elif out_rows[i] < rows and size > 1:
                # chunk-result classes (reduce-scatter, fused apply,
                # attn hop): the result is ONE flat chunk
                got = _unpack_rows(region, ow, 1).astype(npdt)
            else:
                # same-width class: the result keeps the input layout
                got = _unpack_rows(region, ow, slot_chunks[i]).astype(
                    npdt
                )
            per.append(got)
        outs.append(per)
    return outs


def _compiler_params():
    """CompilerParams across jax vintages: modern ``CompilerParams``
    (has_side_effects) when present, else the legacy
    ``TPUCompilerParams`` surface (collective id 5 — the module
    namespace holds 0=ring, 1=put, 2=attention, 3=alltoall, 4=int8
    scale leg, 5=this sequencer)."""
    if hasattr(pltpu, "CompilerParams"):
        return pltpu.CompilerParams(has_side_effects=True, collective_id=5)
    return pltpu.TPUCompilerParams(collective_id=5)  # pragma: no cover


@lru_cache(maxsize=128)
def _windows_program(mesh_id: int, shape_key: tuple, nwin: int,
                     lowering: str):
    """The jitted backlog program (pallas form): ``(slots_global,
    *slot_globals) -> (status_global, *result_globals)``.  Slot CONTENT
    is data — only the shape signature, backlog length and lowering key
    the cache."""
    from ..driver import _MESHES, AXIS, _smap

    mesh = _MESHES[mesh_id]
    size = mesh.devices.size
    depth, in_ws, out_ws, wires, npdt_name = shape_key
    shape = WindowShape(depth, in_ws, out_ws, wires, npdt_name)
    nslots = nwin * depth
    spec_in = (jax.sharding.PartitionSpec(AXIS),) * (1 + nslots)
    spec_out = (jax.sharding.PartitionSpec(AXIS),) * (1 + nslots)

    def body(slots, *flat_xs):
        me = lax.axis_index(AXIS)
        # the operand width slice FUSED into the program (the engine's
        # prep discipline): raw committed shards may be wider than the
        # slot's in_w — slice, never re-stage on the host
        sliced = [
            x[: shape.in_ws[i % depth]]
            if x.shape[0] > shape.in_ws[i % depth] else x
            for i, x in enumerate(flat_xs)
        ]
        xs = [
            list(sliced[w * depth:(w + 1) * depth]) for w in range(nwin)
        ]
        if lowering == "pallas":
            outs = _pallas_windows(
                slots, xs, AXIS, size, nwin, depth, shape, me=me
            )
        else:
            outs = [
                [
                    _decode_slot_xla(
                        slots[w * depth:(w + 1) * depth],
                        i, xs[w][i], me, size, shape,
                    )
                    for i in range(depth)
                ]
                for w in range(nwin)
            ]
        status = jnp.concatenate(
            [
                status_words(slots[w * depth:(w + 1) * depth])
                for w in range(nwin)
            ],
            axis=0,
        )
        flat = [o for per in outs for o in per]
        return (status, *flat)

    return _smap(mesh, body, spec_in, spec_out)


def run_windows(windows, mesh, shape: WindowShape, lowering: str = "pallas"):
    """Dispatch a BACKLOG of refill windows as one mega-window program
    (the chip-tier persistence form: every window queued at doorbell
    time rides the same launch).  ``windows`` is a list of
    ``(slots_np, slot_globals)`` where ``slot_globals`` are assembled
    flat per-slot globals (the zero-copy assembly of the gang engine).
    Returns ``(status_global, results)`` with ``results[w][i]`` the
    slot's result global; the caller blocks on the status global — THE
    device status words — at its drain points."""
    from jax.sharding import NamedSharding, PartitionSpec

    from ..driver import AXIS, _mesh_key

    nwin = len(windows)
    size = mesh.devices.size
    prog = _windows_program(
        _mesh_key(mesh), shape.key(), nwin, str(lowering)
    )
    tiled = np.concatenate(
        [np.asarray(w[0], np.int32) for w in windows], axis=0
    )
    slots_dev = jax.device_put(
        np.tile(tiled, (size, 1)),
        NamedSharding(mesh, PartitionSpec(AXIS)),
    )
    flat = [g for _, gs in windows for g in gs]
    out = prog(slots_dev, *flat)
    status, results = out[0], list(out[1:])
    depth = shape.depth
    return status, [
        results[w * depth:(w + 1) * depth] for w in range(nwin)
    ]


def status_view(status_global) -> np.ndarray:
    """The drainer's read of the device status words: one addressable
    shard (every rank's copy is identical by construction) as a host
    ``(nwin * depth, 2)`` int32 array of ``(seqn, retcode)``."""
    shard = status_global.addressable_shards[0].data
    return np.asarray(shard).reshape(-1, 2)
