"""The device-resident command ring: slot encoder + persistent sequencer.

Role model: the reference's CCLO firmware run loop — the host enqueues
fixed-width commands into the hostctrl FIFO and the offload kernel's
own loop decodes and executes whole collectives with no host in the
data path (``ccl_offload_control.c`` run loop + ``dma_mover``).  The
TPU analog built here:

* the **host-side encoder** packs a warm collective's plan snapshot
  (op, seqn, count, dtype, reduce function, root, tuning registers)
  into ``CMDRING_SLOT_WORDS`` int32 words — the layout comes from ONE
  table, :data:`accl_tpu.constants.CMDRING_FIELDS`, which the device
  decoder reads too (acclint ``cmdring-slot-layout`` keeps both honest);
* the **sequencer** is one device program per refill window that reads
  the slot words AS DATA on device, decodes each slot in its own loop,
  executes the collective, and writes a ``(seqn, retcode)`` status word
  the host drainer polls.  Opcode, reduce function and root are data —
  the same compiled program serves any mix of warm collectives, so a
  refill never recompiles; only operand shapes key the program cache.

Two lowerings of the same decode loop (selected like every other
algorithm register — see ``backends/xla/cmdring.py``):

* ``"xla"`` — each slot's wire move is one ``lax.all_gather`` and the
  fold/root-select run as data-driven ``jnp.where``/``take`` on the
  gathered blocks.  This is the emulator/CI tier: provable on the
  virtual CPU mesh with no Mosaic.
* ``"pallas"`` — ONE Pallas kernel executes the whole window: per slot
  the gather hops are Mosaic remote DMAs over ICI driven by the ring
  kernels' store-and-relay machine (``ring.relay_allgather_hops``; the
  two-rank form composes ``put.remote_block_put``), and the data-driven
  fold runs on the VPU between hops.  The kernel's own slot loop — not
  host dispatch — sequences the collectives, which is the CCLO claim.

Payloads ride the gather at full window width; results are trimmed by
the host-side adoption (pads are never observed).  Oversized payloads
never get here — the engine falls back to host dispatch above
``CMDRING_MAX_PAYLOAD_BYTES`` (big transfers are bandwidth-bound; the
ring exists to collapse the dispatch floor of small warm windows).
"""

from __future__ import annotations

from functools import lru_cache
from typing import Sequence

import numpy as np

import jax

from ...compat import install as _compat_install

_compat_install()  # legacy-jax shims (shard_map kwargs, lax.axis_size)
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...constants import (
    CMDRING_FIELDS,
    CMDRING_SLOT_WORDS,
    CMDRING_ST_BAD_OP,
    CMDRING_ST_OK,
    CmdOpcode,
    ReduceFunction,
)
from ._common import (
    LANES,
    InterpretArg,
    default_interpret,
    require_mosaic_dtypes,
    sublanes_for,
)
from .put import remote_block_put
from .ring import _neighbors, _ring_barrier, relay_allgather_hops

__all__ = [
    "decode_slot",
    "encode_slot",
    "encode_window",
    "run_window",
    "sequencer_program",
    "status_view",
]

_F = CMDRING_FIELDS  # the one layout table (constants.py)


# ---------------------------------------------------------------------------
# host-side encoder / decoder
# ---------------------------------------------------------------------------


def encode_slot(
    seqn: int,
    opcode: CmdOpcode,
    count: int,
    dtype: int = 0,
    function: ReduceFunction = ReduceFunction.SUM,
    root: int = 0,
    flags: int = 0,
    nseg: int = 1,
) -> np.ndarray:
    """One command slot as ``(CMDRING_SLOT_WORDS,)`` int32 — every field
    written through :data:`CMDRING_FIELDS`, never a literal index."""
    words = np.zeros(CMDRING_SLOT_WORDS, np.int32)
    words[_F["seqn"]] = int(seqn) & 0x7FFFFFFF
    words[_F["opcode"]] = int(opcode)
    words[_F["count"]] = int(count)
    words[_F["dtype"]] = int(dtype)
    words[_F["function"]] = int(function)
    words[_F["root"]] = int(root)
    words[_F["flags"]] = int(flags)
    words[_F["nseg"]] = max(1, int(nseg))
    return words


def decode_slot(words) -> dict:
    """The encoder's inverse (tests / debug dumps / ring introspection)."""
    w = np.asarray(words).reshape(-1)
    if w.size != CMDRING_SLOT_WORDS:
        raise ValueError(
            f"slot has {w.size} words, layout says {CMDRING_SLOT_WORDS}"
        )
    out = {name: int(w[idx]) for name, idx in _F.items()}
    out["opcode"] = CmdOpcode(out["opcode"])
    return out


def encode_window(slots: Sequence[np.ndarray], depth: int) -> np.ndarray:
    """Stack encoded slots into a ``(depth, CMDRING_SLOT_WORDS)`` window,
    NOP-padding the tail (padding slots decode to retcode OK and move no
    payload — the sequencer's idle slots)."""
    if len(slots) > depth:
        raise ValueError(f"{len(slots)} slots into a depth-{depth} window")
    rows = [np.asarray(s, np.int32).reshape(-1) for s in slots]
    while len(rows) < depth:
        rows.append(encode_slot(0, CmdOpcode.NOP, 0))
    return np.stack(rows).astype(np.int32)


# ---------------------------------------------------------------------------
# the shared decode epilogue (both lowerings)
# ---------------------------------------------------------------------------


def _fold_blocks(blocks, own, op, fn, root):
    """Data-driven per-slot epilogue shared by both lowerings:
    ``blocks`` is the list of gathered per-rank blocks (static length =
    world size), ``own`` this rank's operand, and ``op``/``fn``/``root``
    are int32 scalars read from the slot words ON DEVICE — so the traced
    program covers every warm op mix without recompiling.  Selects stay
    static-indexed ``jnp.where`` chains (no dynamic gather): both the
    VPU and the CPU tier lower them."""
    acc_sum = blocks[0]
    acc_max = blocks[0]
    for b in blocks[1:]:
        acc_sum = acc_sum + b
        acc_max = jnp.maximum(acc_max, b)
    reduced = jnp.where(fn == int(ReduceFunction.MAX), acc_max, acc_sum)
    rooted = blocks[0]
    for r in range(1, len(blocks)):
        rooted = jnp.where(root == r, blocks[r], rooted)
    return jnp.where(
        op == int(CmdOpcode.ALLREDUCE),
        reduced,
        jnp.where(op == int(CmdOpcode.BCAST), rooted, own),
    )


def _status_words(slots):
    """Per-slot ``(seqn, retcode)`` status words, computed ON DEVICE from
    the slot data by the same program that executes the window — the
    completion word the host drainer polls."""
    op = slots[:, _F["opcode"]]
    ok = (
        (op == int(CmdOpcode.NOP))
        | (op == int(CmdOpcode.ALLREDUCE))
        | (op == int(CmdOpcode.BCAST))
        | (op == int(CmdOpcode.HALT))
    )
    ret = jnp.where(ok, CMDRING_ST_OK, CMDRING_ST_BAD_OP).astype(jnp.int32)
    return jnp.stack([slots[:, _F["seqn"]], ret], axis=1)


# ---------------------------------------------------------------------------
# the Pallas sequencer kernel (one kernel, N collectives)
# ---------------------------------------------------------------------------


def _sequencer_kernel(axis_name: str, size: int, depth: int, rows: int):
    """One window as ONE Mosaic program: the kernel loop — not host
    dispatch — sequences ``depth`` collectives.  ``rows`` is the
    (uniform, tile-aligned) per-slot payload height; slot ``i`` owns
    ``x_ref[i*rows:(i+1)*rows]``.  Per slot: ring-allgather the block
    via the store-and-relay remote-DMA machine (the two-rank ring
    degenerates to one ``put.remote_block_put`` exchange), then fold
    with the data-driven epilogue.  A neighbor barrier separates window
    slots so slot ``i+1``'s first hop can never overwrite a comm slot
    its consumer is still folding."""

    def kernel(slots_ref, x_ref, o_ref, gathered, carry, comm, send_sem,
               recv_sem, ack_sem):
        me, nxt, prv = _neighbors(axis_name, size)
        for i in range(depth):
            _ring_barrier(nxt, prv)  # doorbell + inter-slot slot-reuse gate
            block = x_ref[pl.ds(i * rows, rows), :]
            gathered[pl.ds(me * rows, rows), :] = block
            if size == 2:
                # two-rank gather IS one neighbor put (the put.py
                # primitive): my block lands in the peer's comm slot
                carry[0] = block
                remote_block_put(
                    carry.at[0],
                    comm.at[0, 0],
                    send_sem.at[0, 0],
                    recv_sem.at[0, 0],
                    nxt,
                )
                gathered[pl.ds(prv * rows, rows), :] = comm[0, 0]
            elif size > 2:
                carry[0] = block

                def place(origin, _j, data):
                    gathered[pl.ds(origin * rows, rows), :] = data

                relay_allgather_hops(
                    place, carry, comm, send_sem, recv_sem, ack_sem,
                    me, nxt, prv, size,
                )
            # decode the slot words from SMEM (scalar reads) and fold
            op = slots_ref[i, _F["opcode"]]
            fn = slots_ref[i, _F["function"]]
            root = slots_ref[i, _F["root"]]
            blocks = [
                gathered[pl.ds(r * rows, rows), :] for r in range(size)
            ]
            o_ref[pl.ds(i * rows, rows), :] = _fold_blocks(
                blocks, block, op, fn, root
            )

    return kernel


def _pallas_window(slots, xs, axis_name, size, depth, take_ws,
                   interpret: InterpretArg = None):
    """Trace the whole window through one ``pallas_call``.  Per-slot
    operands are packed to one uniform tile-aligned height inside the
    traced body (zero extra dispatch — this all runs in the SAME
    program), the kernel executes every slot, and the per-slot results
    are unpacked back to their true widths."""
    dtype = xs[0].dtype
    interp = default_interpret(interpret)
    require_mosaic_dtypes(interp, "command-ring sequencer", dtype)
    sub = sublanes_for(dtype)
    width = max(take_ws)
    rows = max(-(-width // LANES), 1)
    rows = -(-rows // sub) * sub  # tile-aligned uniform slot height
    packed = []
    for x, w in zip(xs, take_ws):
        flat = x[:w]
        pad = rows * LANES - w
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), dtype)])
        packed.append(flat.reshape(rows, LANES))
    xp = jnp.concatenate(packed, axis=0)  # (depth*rows, LANES)
    scratch = [
        pltpu.VMEM((size * rows, LANES), dtype),  # gathered blocks
        pltpu.VMEM((1, rows, LANES), dtype),      # relay carry
        pltpu.VMEM((2, 1, rows, LANES), dtype),   # comm slots
        pltpu.SemaphoreType.DMA((2, 1)),          # send
        pltpu.SemaphoreType.DMA((2, 1)),          # recv
        pltpu.SemaphoreType.REGULAR((2, 1)),      # slot acks
    ]
    out = pl.pallas_call(
        _sequencer_kernel(axis_name, size, depth, rows),
        out_shape=jax.ShapeDtypeStruct((depth * rows, LANES), dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=scratch,
        compiler_params=_compiler_params(),
        interpret=interp,
    )(slots, xp)
    outs = []
    for i, w in enumerate(take_ws):
        outs.append(out[i * rows:(i + 1) * rows].reshape(-1)[:w])
    return outs


def _compiler_params():
    """CompilerParams across jax vintages: modern ``CompilerParams``
    (has_side_effects) when present, else the legacy
    ``TPUCompilerParams`` surface (collective id 5 — the module
    namespace holds 0=ring, 1=put, 2=attention, 3=alltoall, 4=int8
    scale leg, 5=this sequencer)."""
    if hasattr(pltpu, "CompilerParams"):
        return pltpu.CompilerParams(has_side_effects=True, collective_id=5)
    return pltpu.TPUCompilerParams(collective_id=5)  # pragma: no cover


# ---------------------------------------------------------------------------
# the sequencer program (one dispatch per refill window)
# ---------------------------------------------------------------------------


@lru_cache(maxsize=256)
def _program(mesh_id: int, depth: int, widths: tuple, take_ws: tuple,
             lowering: str):
    """The jitted refill program: ``(slots_global, *slot_globals) ->
    (status_global, *result_globals)``.  Slot CONTENT is data — only
    the window shape (depth, per-slot widths) and the lowering key the
    cache, so a warm ring session never recompiles on op/function/root
    churn."""
    from ..driver import _MESHES, AXIS, _smap

    mesh = _MESHES[mesh_id]
    size = mesh.devices.size
    spec_in = (jax.sharding.PartitionSpec(AXIS),) * (1 + depth)
    spec_out = (jax.sharding.PartitionSpec(AXIS),) * (1 + depth)

    def body(slots, *xs):
        # slots: this rank's (depth, CMDRING_SLOT_WORDS) replica shard
        if lowering == "pallas":
            outs = _pallas_window(
                slots, xs, AXIS, size, depth, list(take_ws)
            )
        else:
            outs = []
            for i in range(depth):
                own = xs[i][:take_ws[i]]
                # the slot's wire move: ONE gather; fold/root-select are
                # data-driven on the gathered stack
                gathered = lax.all_gather(own, AXIS)
                blocks = [gathered[r] for r in range(size)]
                outs.append(_fold_blocks(
                    blocks, own,
                    slots[i, _F["opcode"]],
                    slots[i, _F["function"]],
                    slots[i, _F["root"]],
                ))
        return (_status_words(slots), *outs)

    return _smap(mesh, body, spec_in, spec_out)


def sequencer_program(mesh, depth: int, widths: Sequence[int],
                      take_ws: Sequence[int], lowering: str = "xla"):
    """Prepared-program handle for a ring session (the engine caches it
    per window shape, exactly like ``opdriver.prepare``)."""
    from ..driver import _mesh_key

    return _program(
        _mesh_key(mesh), int(depth), tuple(int(w) for w in widths),
        tuple(int(w) for w in take_ws), str(lowering),
    )


def run_window(slots_np: np.ndarray, globals_, mesh, take_ws,
               lowering: str = "xla"):
    """Dispatch one refill window: ``slots_np`` is the host ring's
    ``(depth, CMDRING_SLOT_WORDS)`` int32 view, ``globals_`` one
    assembled flat global per slot (raw per-rank HBM shards — the
    zero-copy assembly of the gang engine).  Returns
    ``(status_global, result_globals)``; the caller blocks on the
    status global — THE device status word — at its drain points."""
    from jax.sharding import NamedSharding, PartitionSpec

    from ..driver import AXIS

    depth = int(slots_np.shape[0])
    size = mesh.devices.size
    widths = tuple(int(g.shape[0]) // size for g in globals_)
    prog = sequencer_program(mesh, depth, widths, take_ws, lowering)
    # the refill write: the slot words land in device memory as part of
    # THIS dispatch (slots ride the program call — one host interaction
    # per refill, the counter-asserted contract)
    tiled = np.tile(np.asarray(slots_np, np.int32), (size, 1))
    slots_dev = jax.device_put(
        tiled, NamedSharding(mesh, PartitionSpec(AXIS))
    )
    out = prog(slots_dev, *globals_)
    return out[0], list(out[1:])


def status_view(status_global) -> np.ndarray:
    """The drainer's read of the device status word: one addressable
    shard (every rank's copy is identical by construction) as a host
    ``(depth, 2)`` int32 array of ``(seqn, retcode)``."""
    shard = status_global.addressable_shards[0].data
    return np.asarray(shard).reshape(-1, 2)
