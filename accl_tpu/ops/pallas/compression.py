"""Wire-compression kernels — the ``hp_compression`` plugin as TPU kernels.

The reference casts fp32<->fp16 on 512-bit stream lanes before/after the
wire (/root/reference/kernels/plugins/hp_compression/hp_compression.cpp:
30-80; three instances cover two operand lanes and the result lane).  The
TPU-native equivalents:

* ``cast`` — dtype conversion as a tiled VPU pass, with optional
  **stochastic rounding** (pltpu.stochastic_round + on-chip PRNG) so
  repeated compressed reductions stay unbiased — a capability the FPGA
  plugin lacks.
* ``quantize_int8`` / ``dequantize_int8`` — blockwise int8 wire format
  with per-tile scales, extending the compression surface beyond the
  reference's half-precision-only lane.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import (
    LANES,
    InterpretArg,
    block_rows,
    default_interpret,
    mosaic_rejects,
    pack_lanes,
    unpack_lanes,
)


def _cast_kernel(out_dtype):
    def kernel(x_ref, o_ref):
        o_ref[:] = x_ref[:].astype(out_dtype)

    return kernel


def _stochastic_cast_kernel(out_dtype):
    # f32 -> bf16 stochastic rounding by hand (portable to the interpreter):
    # add uniform random bits to the 16 mantissa bits that truncation drops,
    # then keep the top half-word.  Non-finite values fall back to the
    # deterministic cast.
    def kernel(seed_ref, x_ref, o_ref):
        # mix the grid position into the seed so every block draws
        # independent bits (one seed stream per tile, not one reused one)
        pltpu.prng_seed(seed_ref[0] + pl.program_id(0))
        x = x_ref[:]
        rand = pltpu.bitcast(pltpu.prng_random_bits(x.shape), jnp.uint32)
        u = pltpu.bitcast(x, jnp.uint32)
        rounded = u + (rand & jnp.uint32(0xFFFF))
        bf = pltpu.bitcast(
            (rounded >> 16).astype(jnp.uint16), jnp.bfloat16
        )
        o_ref[:] = jnp.where(jnp.isfinite(x), bf, x.astype(out_dtype))

    return kernel


def cast(
    x: jax.Array,
    dtype,
    *,
    stochastic: bool = False,
    seed: int = 0,
    interpret: InterpretArg = None,
) -> jax.Array:
    """Convert ``x`` to ``dtype`` in a tiled kernel pass.

    ``stochastic=True`` (fp32 -> bfloat16 only) rounds stochastically using
    the per-core PRNG, keeping compressed-reduction pipelines unbiased.
    (Note: the Pallas TPU *interpreter* stubs ``prng_random_bits`` to
    zeros, so off-TPU the stochastic path degenerates to truncation —
    randomness is a hardware-tier property.)

    float16 endpoints never reach Mosaic: the TPU mosaic dialect has no
    ``f16`` (measured on v5e: the AOT compile rejects the kernel, and a
    failed remote compile aborts the whole client session), so compiled-
    mode f16 casts ride XLA's convert instead — numerically identical
    (both round to nearest even), and fp16 is a wire/storage format here,
    not a compute one.  The interpreter tier still runs the kernel.
    """
    dtype = jnp.dtype(dtype)
    interp = default_interpret(interpret)
    if not stochastic and mosaic_rejects(interp, x.dtype, dtype):
        return x.astype(dtype)
    xp, n = pack_lanes(x)
    rows = xp.shape[0]
    br = block_rows(rows)
    grid = (rows // br,)
    spec = pl.BlockSpec((br, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM)
    out_shape = jax.ShapeDtypeStruct((rows, LANES), dtype)

    if stochastic:
        if x.dtype != jnp.float32 or dtype != jnp.bfloat16:
            raise ValueError(
                "stochastic rounding supports float32 -> bfloat16"
            )
        # index maps under scalar prefetch also receive the scalar ref
        pspec = pl.BlockSpec(
            (br, LANES), lambda i, seed_ref: (i, 0), memory_space=pltpu.VMEM
        )
        out = pl.pallas_call(
            _stochastic_cast_kernel(dtype),
            out_shape=out_shape,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=grid,
                in_specs=[pspec],
                out_specs=pspec,
            ),
            interpret=interp,
        )(jnp.asarray([seed], jnp.int32), xp)
    else:
        out = pl.pallas_call(
            _cast_kernel(dtype),
            out_shape=out_shape,
            grid=grid,
            in_specs=[spec],
            out_specs=spec,
            interpret=interp,
        )(xp)
    return unpack_lanes(out, n, x.shape)


def _quantize_kernel(scales_ref, x_ref, values_ref):
    # per-tile scale arrives via scalar prefetch (SMEM); outputs that are
    # revisited across grid steps ((1,1) SMEM blocks, or whole-array
    # outputs written one slot per step) either fail to lower or wedge
    # the TPU runtime under fori_loop, so the kernel never writes scales
    # — the XLA pre-pass computes them
    scale = scales_ref[pl.program_id(0)]
    values_ref[:] = jnp.clip(
        jnp.round(x_ref[:] / scale), -127, 127
    ).astype(jnp.int8)


def _dequantize_kernel(scales_ref, values_ref, o_ref):
    o_ref[:] = (
        values_ref[:].astype(jnp.float32) * scales_ref[pl.program_id(0)]
    )


def _tile_specs(br: int):
    # index maps under scalar prefetch also receive the scalar ref
    return pl.BlockSpec(
        (br, LANES), lambda i, s_ref: (i, 0), memory_space=pltpu.VMEM
    )


def quantize_int8(
    x: jax.Array, *, interpret: InterpretArg = None
):
    """Blockwise int8 quantization: returns ``(values, scales, n)`` where
    each grid tile carries one fp32 scale (absmax / 127).

    The scales are an XLA reduction pass over the tiles; the Pallas kernel
    consumes them as scalar-prefetch operands and emits only the lane-
    aligned int8 payload."""
    xp, n = pack_lanes(x.astype(jnp.float32))
    rows = xp.shape[0]
    br = block_rows(rows)
    nblk = rows // br
    scales = jnp.maximum(
        jnp.max(jnp.abs(xp.reshape(nblk, br * LANES)), axis=1) / 127.0,
        1e-30,
    )
    values = pl.pallas_call(
        _quantize_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.int8),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nblk,),
            in_specs=[_tile_specs(br)],
            out_specs=_tile_specs(br),
        ),
        interpret=default_interpret(interpret),
    )(scales, xp)
    return values, scales.reshape(nblk, 1), n


def dequantize_int8(
    values: jax.Array,
    scales: jax.Array,
    n: int,
    shape,
    dtype=jnp.float32,
    *,
    interpret: InterpretArg = None,
) -> jax.Array:
    """Inverse of :func:`quantize_int8`.  ``dtype`` restores the original
    operand dtype (quantization always computes in float32)."""
    rows = values.shape[0]
    nblk = scales.shape[0]
    br = rows // nblk
    out = pl.pallas_call(
        _dequantize_kernel,
        out_shape=jax.ShapeDtypeStruct((rows, LANES), jnp.float32),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nblk,),
            in_specs=[_tile_specs(br)],
            out_specs=_tile_specs(br),
        ),
        interpret=default_interpret(interpret),
    )(scales.reshape(-1), values)
    return unpack_lanes(out, n, shape, dtype=dtype)
