"""Ring attention as one Pallas kernel: K/V blocks rotate over ICI while
the MXU folds the visiting block — wire/compute overlap *inside* the
kernel.

The model-level ``models.ring_attention`` expresses the rotation as
``lax.ppermute`` hops and leaves overlap to XLA's scheduler.  This kernel
owns the schedule the way the reference firmware owns its segmented ring
hot loop (ccl_offload_control.c:1888-2071 — recv/reduce/send of hop ``s``
overlapped explicitly): at every hop the *next* remote DMA is launched
first, then the just-arrived K/V block is folded into the online-softmax
state while the wire runs.  Slot reuse is ack-gated exactly like
``ops.pallas.ring`` (the RX-buffer release protocol).

Layout: per device q/k/v are ``(BH, T, D)`` — batch x heads folded into
the leading dim, D padded to the 128-lane width by the wrapper.  The
online-softmax state (running numerator, max, denominator) lives in VMEM
scratch in float32 regardless of input dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import (
    LANES,
    InterpretArg,
    ack_gate,
    ack_release,
    default_interpret,
    neighbor_barrier,
)

_NEG = -1e30


def _fold(bh, q_ref, k_blk_ref, v_blk_ref, o_acc, m_ref, l_ref, mask, scale):
    """Fold one visiting K/V block into (o, m, l) for batch-head ``bh``.

    Matmul operands stay in the input dtype (bf16 keeps the MXU on its
    fast path; an f32 upcast quarters throughput on v5e) with f32
    accumulation via preferred_element_type; only the softmax state is
    f32."""
    q = q_ref[bh]
    k_blk = k_blk_ref[bh]
    v_blk = v_blk_ref[bh]
    scores = jax.lax.dot_general(
        q, k_blk,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    scores = jnp.where(mask, scores, _NEG)
    m_old = m_ref[bh][:, :1]
    m_new = jnp.maximum(m_old, scores.max(axis=-1, keepdims=True))
    p = jnp.exp(scores - m_new)
    alpha = jnp.exp(m_old - m_new)
    o_acc[bh] = o_acc[bh] * alpha + jax.lax.dot_general(
        p.astype(v_blk.dtype), v_blk,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    l_ref[bh] = jnp.broadcast_to(
        l_ref[bh][:, :1] * alpha + p.sum(axis=-1, keepdims=True),
        l_ref[bh].shape,
    )
    m_ref[bh] = jnp.broadcast_to(m_new, m_ref[bh].shape)


def _attention_kernel(axis_name, size, causal, scale, striped=False):
    total_hops = size - 1

    def kernel(q_ref, k_ref, v_ref, o_ref,
               o_acc, m_ref, l_ref, comm, send_sem, recv_sem, ack_sem):
        BH, T, D = q_ref.shape
        me = lax.axis_index(axis_name)
        nxt = jnp.where(me + 1 == size, 0, me + 1)
        prv = jnp.where(me == 0, size - 1, me - 1)

        rows = lax.broadcasted_iota(jnp.int32, (T, T), 0)
        cols = lax.broadcasted_iota(jnp.int32, (T, T), 1)
        tri = rows >= cols
        tri_strict = rows > cols
        ones = jnp.ones((T, T), jnp.bool_)

        def mask_for(origin):
            if not causal:
                return ones
            if striped:
                # round-robin token layout (models.stripe_sequence):
                # global q pos = tq*P + me, k pos = tk*P + origin, so the
                # mask is triangular for EVERY (rank, origin) pair — the
                # causal work balances across the ring
                return jnp.where(me >= origin, tri, tri_strict)
            return jnp.where(
                origin == me, tri,
                jnp.where(origin < me, ones, jnp.zeros((T, T), jnp.bool_)),
            )

        # init state + fold the local block
        for bh in range(BH):
            o_acc[bh] = jnp.zeros((T, D), jnp.float32)
            m_ref[bh] = jnp.full((T, LANES), _NEG, jnp.float32)
            l_ref[bh] = jnp.zeros((T, LANES), jnp.float32)

        if size > 1:
            neighbor_barrier(nxt, prv)

            # hop 1 in flight before any compute: send local K/V to next
            def start_hop(hop, src_k, src_v):
                slot = hop % 2
                ack_gate(ack_sem.at[slot], hop, value=2)  # 2 DMAs (K+V)
                for which, src in ((0, src_k), (1, src_v)):
                    pltpu.make_async_remote_copy(
                        src_ref=src,
                        dst_ref=comm.at[slot, which],
                        send_sem=send_sem.at[slot, which],
                        recv_sem=recv_sem.at[slot, which],
                        device_id=nxt,
                        device_id_type=pltpu.DeviceIdType.LOGICAL,
                    ).start()

            def wait_hop(hop):
                slot = hop % 2
                for which in (0, 1):
                    pltpu.make_async_remote_copy(
                        src_ref=comm.at[slot, which],
                        dst_ref=comm.at[slot, which],
                        send_sem=send_sem.at[slot, which],
                        recv_sem=recv_sem.at[slot, which],
                        device_id=nxt,
                        device_id_type=pltpu.DeviceIdType.LOGICAL,
                    ).wait()

            start_hop(1, k_ref, v_ref)

        for bh in range(BH):
            _fold(bh, q_ref, k_ref, v_ref, o_acc, m_ref, l_ref,
                  mask_for(me), scale)

        for s in range(1, size):
            slot = s % 2
            wait_hop(s)  # K/V block s landed; send side of hop s drained
            # hop s's send read comm[(s-1)%2]; that drain (just waited) is
            # what frees the *previous* slot for the upstream neighbor —
            # acking any earlier would let prv overwrite a slot the
            # forwarding DMA is still reading (real race, caught by the
            # interpreter's detector).  Signal only while a future hop
            # (s+1 <= P-1 at prv) will consume the ack.
            if s >= 2:  # hop 1 sent from the input refs, not a comm slot
                ack_release(
                    ack_sem.at[(s - 1) % 2], s - 1, total_hops, prv, value=2
                )
            if s + 1 < size:
                # launch the next rotation *before* folding: the wire moves
                # hop s+1 while the MXU folds hop s (the overlap the
                # firmware gets from its segmented move pipeline)
                start_hop(s + 1, comm.at[slot, 0], comm.at[slot, 1])
            origin = jnp.mod(me - s, size)
            for bh in range(BH):
                _fold(bh, q_ref, comm.at[slot, 0], comm.at[slot, 1],
                      o_acc, m_ref, l_ref, mask_for(origin), scale)

        for bh in range(BH):
            o_ref[bh] = (
                o_acc[bh] / jnp.maximum(l_ref[bh][:, :1], 1e-30)
            ).astype(o_ref.dtype)

    return kernel


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = True,
    *,
    striped: bool = False,
    collective_id: int = 2,
    interpret: InterpretArg = None,
) -> jax.Array:
    """Sequence-parallel attention in one Pallas kernel.

    q, k, v: ``(B, H, T_local, D)`` per device inside ``shard_map`` over a
    1-D mesh axis (sequence axis sharded).  Returns ``(B, H, T_local, D)``.
    D is padded to 128 lanes internally; T_local must be a multiple of 8.

    ``striped=True`` expects round-robin (striped) sequence shards
    (``models.stripe_sequence``): every hop's causal mask is then
    triangular, balancing the causal work across the ring instead of
    idling early ranks (Striped Attention) — same wire, same fold.
    """
    B, H, T, D = q.shape
    if k.shape != q.shape or v.shape != q.shape:
        raise ValueError(
            f"q/k/v shapes must match, got {q.shape}/{k.shape}/{v.shape}"
        )
    if k.dtype != q.dtype or v.dtype != q.dtype:
        raise ValueError(
            f"q/k/v dtypes must match (comm slots and DMAs are typed from "
            f"q), got {q.dtype}/{k.dtype}/{v.dtype}"
        )
    if T % 8:
        raise ValueError("T_local must be a multiple of 8")
    size = lax.axis_size(axis_name)
    scale = 1.0 / (D ** 0.5)  # scale by the *logical* head dim, not padded

    pad = (-D) % LANES
    if pad:
        padding = [(0, 0)] * 3 + [(0, pad)]
        q, k, v = (jnp.pad(a, padding) for a in (q, k, v))
    Dp = D + pad

    qf = q.reshape(B * H, T, Dp)
    kf = k.reshape(B * H, T, Dp)
    vf = v.reshape(B * H, T, Dp)

    out = pl.pallas_call(
        _attention_kernel(axis_name, size, causal, scale, striped),
        out_shape=jax.ShapeDtypeStruct((B * H, T, Dp), q.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 3,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((B * H, T, Dp), jnp.float32),   # o accumulator
            pltpu.VMEM((B * H, T, LANES), jnp.float32),  # running max
            pltpu.VMEM((B * H, T, LANES), jnp.float32),  # running denom
            pltpu.VMEM((2, 2, B * H, T, Dp), q.dtype),   # K/V comm slots
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=default_interpret(interpret),
    )(qf, kf, vf)
    out = out.reshape(B, H, T, Dp)
    return out[..., :D] if pad else out


# ---------------------------------------------------------------------------
# single-chip flash attention (no ring): the local fused forward
# ---------------------------------------------------------------------------


def _flash_kernel(causal, scale, bq, bk, nkb, t_real):
    """One grid step computes one (bq, D) output block: fold the visiting
    k/v blocks with online softmax.  Outputs are written exactly once per
    grid step (blocked o spec) — no grid-revisited outputs, the construct
    this box's tunnel cannot tolerate."""

    def kernel(q_ref, k_ref, v_ref, o_ref):
        iq = pl.program_id(1)
        # operands stay in the input dtype (bf16 MXU fast path); the
        # scale folds into the f32 scores, the softmax state is f32
        q = q_ref[0]  # (bq, D)
        q_pos = iq * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)

        def fold(j, carry):
            m, l, acc = carry
            kb = k_ref[0, pl.ds(j * bk, bk), :]
            vb = v_ref[0, pl.ds(j * bk, bk), :]
            s = jax.lax.dot_general(
                q, kb,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            ) * scale
            k_pos = j * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            mask = k_pos < t_real
            if causal:
                mask &= q_pos >= k_pos
            s = jnp.where(mask, s, _NEG)
            m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + p.sum(axis=-1, keepdims=True)
            acc_new = acc * alpha + jax.lax.dot_general(
                p.astype(vb.dtype), vb,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return m_new, l_new, acc_new

        init = (
            jnp.full((bq, 1), _NEG, jnp.float32),
            jnp.zeros((bq, 1), jnp.float32),
            jnp.zeros((bq, q.shape[-1]), jnp.float32),
        )
        # causal early exit: with bq == bk, q block iq only sees k blocks
        # 0..iq (dynamic trip count — Mosaic lowers it to a while loop)
        hi = jnp.minimum(iq + 1, nkb) if causal else nkb
        m, l, acc = lax.fori_loop(0, hi, fold, init)
        o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)

    return kernel


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    *,
    block: int = 256,
    interpret: InterpretArg = None,
) -> jax.Array:
    """Local (single-chip) fused attention: ``(B, H, T, D) -> same`` with
    the (T, T) score matrix never leaving VMEM — the kernel-owned form of
    ``ops.attention.blockwise_attention`` (which is the trainable XLA
    fold; this one hand-owns the schedule like the ring kernels own
    theirs).  Forward-only: serving/prefill paths; training uses the
    differentiable XLA form.

    K/V live whole in VMEM per (batch*head) grid step — sized for
    serving sequence lengths (T <= ~8K at 128 lanes); the ring kernel
    covers longer sequences across chips."""
    from ._common import sublanes_for

    B, H, T, D = q.shape
    if k.shape != q.shape or v.shape != q.shape:
        raise ValueError(
            f"q/k/v shapes must match, got {q.shape}/{k.shape}/{v.shape}"
        )
    scale = 1.0 / (D ** 0.5)
    # block height must be a sublane multiple (f32 8 / bf16 16 / int8 32)
    # or Mosaic rejects the VMEM tile; short sequences round T UP to the
    # sublane grid and pad, they don't shrink the tile below it
    sub = sublanes_for(q.dtype)
    bq = bk = min(
        max(block // sub * sub, sub),
        (T + sub - 1) // sub * sub,
    )
    padT = (-T) % bq
    padD = (-D) % LANES
    if padT or padD:
        padding = [(0, 0), (0, 0), (0, padT), (0, padD)]
        q, k, v = (jnp.pad(a, padding) for a in (q, k, v))
    Tp, Dp = T + padT, D + padD
    nq, nkb = Tp // bq, Tp // bk

    qf = q.reshape(B * H, Tp, Dp)
    kf = k.reshape(B * H, Tp, Dp)
    vf = v.reshape(B * H, Tp, Dp)

    out = pl.pallas_call(
        _flash_kernel(causal, scale, bq, bk, nkb, T),
        grid=(B * H, nq),
        out_shape=jax.ShapeDtypeStruct((B * H, Tp, Dp), q.dtype),
        in_specs=[
            pl.BlockSpec((1, bq, Dp), lambda bh, iq: (bh, iq, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp, Dp), lambda bh, iq: (bh, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp, Dp), lambda bh, iq: (bh, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, bq, Dp), lambda bh, iq: (bh, iq, 0),
            memory_space=pltpu.VMEM,
        ),
        interpret=default_interpret(interpret),
    )(qf, kf, vf)
    out = out.reshape(B, H, Tp, Dp)
    return out[:, :, :T, :D]
