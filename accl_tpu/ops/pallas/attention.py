"""Ring attention as one Pallas kernel: K/V blocks rotate over ICI while
the MXU folds the visiting block — wire/compute overlap *inside* the
kernel.

The model-level ``models.ring_attention`` expresses the rotation as
``lax.ppermute`` hops and leaves overlap to XLA's scheduler.  This kernel
owns the schedule the way the reference firmware owns its segmented ring
hot loop (ccl_offload_control.c:1888-2071 — recv/reduce/send of hop ``s``
overlapped explicitly): at every hop the *next* remote DMA is launched
first, then the just-arrived K/V block is folded into the online-softmax
state while the wire runs.  Slot reuse is ack-gated exactly like
``ops.pallas.ring`` (the RX-buffer release protocol).

Layout: per device q/k/v are ``(BH, T, D)`` — batch x heads folded into
the leading dim, D padded to the 128-lane width by the wrapper.  The
online-softmax state (running numerator, max, denominator) lives in VMEM
scratch in float32 regardless of input dtype.
"""

from __future__ import annotations

import functools

import jax

from ...compat import install as _compat_install

_compat_install()  # legacy-jax shims (shard_map kwargs, lax.axis_size)
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import (
    LANES,
    InterpretArg,
    ack_gate,
    ack_release,
    default_interpret,
    require_mosaic_dtypes,
    neighbor_barrier,
)



_NEG = -1e30


def _mxu_precision(dtype):
    """Dot precision for the attention kernels, from the operand dtype.

    The MXU's DEFAULT precision multiplies f32 operands in ONE bf16 pass
    (measured on v5e: 1.4e-1 max error on a 128x128 f32 matmul vs 6e-6
    under HIGHEST) — fine for bf16 training, but it silently downgrades
    an f32 kernel contract, and the interpreter tier (exact f32) would
    never show it.  f32 operands therefore request the multi-pass mode;
    bf16/int8 keep DEFAULT (single pass, already exact for their
    inputs)."""
    return (
        lax.Precision.HIGHEST
        if jnp.dtype(dtype) == jnp.float32 else None
    )


def attn_hop_partial(q, kv, scale):
    """One FUSED_ATTN_HOP epilogue: the scaled elementwise partial of the
    resident q block against the kv block that just arrived on the relay
    (the sequencer's flat-row form of a hop's score contribution — the
    blocked kernel above folds full (T, D) tiles; a fused slot streams
    the same hop product per lane row).  Shared by both sequencer
    lowerings and the engine's host-decomposition reference so the slot
    semantics have exactly one definition.  Works on jnp and numpy
    operands alike."""
    return (q * kv) * scale


def _fold(bh, q_ref, k_blk_ref, v_blk_ref, o_acc, m_ref, l_ref, mask, scale):
    """Fold one visiting K/V block into (o, m, l) for batch-head ``bh``.

    Matmul operands stay in the input dtype (bf16 keeps the MXU on its
    fast path; an f32 upcast quarters throughput on v5e) with f32
    accumulation via preferred_element_type; only the softmax state is
    f32."""
    q = q_ref[bh]
    k_blk = k_blk_ref[bh]
    v_blk = v_blk_ref[bh]
    scores = jax.lax.dot_general(
        q, k_blk,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=_mxu_precision(q.dtype),
    ) * scale
    scores = jnp.where(mask, scores, _NEG)
    m_old = m_ref[bh][:, :1]
    m_new = jnp.maximum(m_old, scores.max(axis=-1, keepdims=True))
    p = jnp.exp(scores - m_new)
    alpha = jnp.exp(m_old - m_new)
    o_acc[bh] = o_acc[bh] * alpha + jax.lax.dot_general(
        p.astype(v_blk.dtype), v_blk,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=_mxu_precision(v_blk.dtype),
    )
    l_ref[bh] = jnp.broadcast_to(
        l_ref[bh][:, :1] * alpha + p.sum(axis=-1, keepdims=True),
        l_ref[bh].shape,
    )
    m_ref[bh] = jnp.broadcast_to(m_new, m_ref[bh].shape)


def _attention_kernel(axis_name, size, causal, scale, striped=False):
    total_hops = size - 1

    def kernel(q_ref, k_ref, v_ref, o_ref,
               o_acc, m_ref, l_ref, comm, send_sem, recv_sem, ack_sem):
        BH, T, D = q_ref.shape
        me = lax.axis_index(axis_name)
        nxt = jnp.where(me + 1 == size, 0, me + 1)
        prv = jnp.where(me == 0, size - 1, me - 1)

        rows = lax.broadcasted_iota(jnp.int32, (T, T), 0)
        cols = lax.broadcasted_iota(jnp.int32, (T, T), 1)
        tri = rows >= cols
        tri_strict = rows > cols
        ones = jnp.ones((T, T), jnp.bool_)

        def mask_for(origin):
            if not causal:
                return ones
            if striped:
                # round-robin token layout (models.stripe_sequence):
                # global q pos = tq*P + me, k pos = tk*P + origin, so the
                # mask is triangular for EVERY (rank, origin) pair — the
                # causal work balances across the ring
                return jnp.where(me >= origin, tri, tri_strict)
            return jnp.where(
                origin == me, tri,
                jnp.where(origin < me, ones, jnp.zeros((T, T), jnp.bool_)),
            )

        # init state + fold the local block
        for bh in range(BH):
            o_acc[bh] = jnp.zeros((T, D), jnp.float32)
            m_ref[bh] = jnp.full((T, LANES), _NEG, jnp.float32)
            l_ref[bh] = jnp.zeros((T, LANES), jnp.float32)

        if size > 1:
            neighbor_barrier(nxt, prv)

            # hop 1 in flight before any compute: send local K/V to next
            def start_hop(hop, src_k, src_v):
                slot = hop % 2
                ack_gate(ack_sem.at[slot], hop, value=2)  # 2 DMAs (K+V)
                for which, src in ((0, src_k), (1, src_v)):
                    pltpu.make_async_remote_copy(
                        src_ref=src,
                        dst_ref=comm.at[slot, which],
                        send_sem=send_sem.at[slot, which],
                        recv_sem=recv_sem.at[slot, which],
                        device_id=nxt,
                        device_id_type=pltpu.DeviceIdType.LOGICAL,
                    ).start()

            def wait_hop(hop):
                slot = hop % 2
                for which in (0, 1):
                    pltpu.make_async_remote_copy(
                        src_ref=comm.at[slot, which],
                        dst_ref=comm.at[slot, which],
                        send_sem=send_sem.at[slot, which],
                        recv_sem=recv_sem.at[slot, which],
                        device_id=nxt,
                        device_id_type=pltpu.DeviceIdType.LOGICAL,
                        # acclint: allow[unbounded-wait] Mosaic-traced DMA
                        # semaphore wait: no timeout form exists in Pallas;
                        # the host watchdog bounds the whole program
                    ).wait()

            start_hop(1, k_ref, v_ref)

        for bh in range(BH):
            _fold(bh, q_ref, k_ref, v_ref, o_acc, m_ref, l_ref,
                  mask_for(me), scale)

        for s in range(1, size):
            slot = s % 2
            wait_hop(s)  # K/V block s landed; send side of hop s drained
            # hop s's send read comm[(s-1)%2]; that drain (just waited) is
            # what frees the *previous* slot for the upstream neighbor —
            # acking any earlier would let prv overwrite a slot the
            # forwarding DMA is still reading (real race, caught by the
            # interpreter's detector).  Signal only while a future hop
            # (s+1 <= P-1 at prv) will consume the ack.
            if s >= 2:  # hop 1 sent from the input refs, not a comm slot
                ack_release(
                    ack_sem.at[(s - 1) % 2], s - 1, total_hops, prv, value=2
                )
            if s + 1 < size:
                # launch the next rotation *before* folding: the wire moves
                # hop s+1 while the MXU folds hop s (the overlap the
                # firmware gets from its segmented move pipeline)
                start_hop(s + 1, comm.at[slot, 0], comm.at[slot, 1])
            origin = jnp.mod(me - s, size)
            for bh in range(BH):
                _fold(bh, q_ref, comm.at[slot, 0], comm.at[slot, 1],
                      o_acc, m_ref, l_ref, mask_for(origin), scale)

        for bh in range(BH):
            o_ref[bh] = (
                o_acc[bh] / jnp.maximum(l_ref[bh][:, :1], 1e-30)
            ).astype(o_ref.dtype)

    return kernel


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = True,
    *,
    striped: bool = False,
    collective_id: int = 2,
    interpret: InterpretArg = None,
) -> jax.Array:
    """Sequence-parallel attention in one Pallas kernel.

    q, k, v: ``(B, H, T_local, D)`` per device inside ``shard_map`` over a
    1-D mesh axis (sequence axis sharded).  Returns ``(B, H, T_local, D)``.
    D is padded to 128 lanes internally; T_local must be a multiple of 8.

    ``striped=True`` expects round-robin (striped) sequence shards
    (``models.stripe_sequence``): every hop's causal mask is then
    triangular, balancing the causal work across the ring instead of
    idling early ranks (Striped Attention) — same wire, same fold.
    """
    B, H, T, D = q.shape
    if k.shape != q.shape or v.shape != q.shape:
        raise ValueError(
            f"q/k/v shapes must match, got {q.shape}/{k.shape}/{v.shape}"
        )
    if k.dtype != q.dtype or v.dtype != q.dtype:
        raise ValueError(
            f"q/k/v dtypes must match (comm slots and DMAs are typed from "
            f"q), got {q.dtype}/{k.dtype}/{v.dtype}"
        )
    if T % 8:
        raise ValueError("T_local must be a multiple of 8")
    require_mosaic_dtypes(default_interpret(interpret), "ring attention",
                          q.dtype)
    size = lax.axis_size(axis_name)
    scale = 1.0 / (D ** 0.5)  # scale by the *logical* head dim, not padded

    pad = (-D) % LANES
    if pad:
        padding = [(0, 0)] * 3 + [(0, pad)]
        q, k, v = (jnp.pad(a, padding) for a in (q, k, v))
    Dp = D + pad

    qf = q.reshape(B * H, T, Dp)
    kf = k.reshape(B * H, T, Dp)
    vf = v.reshape(B * H, T, Dp)

    out = pl.pallas_call(
        _attention_kernel(axis_name, size, causal, scale, striped),
        out_shape=jax.ShapeDtypeStruct((B * H, T, Dp), q.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 3,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((B * H, T, Dp), jnp.float32),   # o accumulator
            pltpu.VMEM((B * H, T, LANES), jnp.float32),  # running max
            pltpu.VMEM((B * H, T, LANES), jnp.float32),  # running denom
            pltpu.VMEM((2, 2, B * H, T, Dp), q.dtype),   # K/V comm slots
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.SemaphoreType.REGULAR((2,)),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=default_interpret(interpret),
    )(qf, kf, vf)
    out = out.reshape(B, H, T, Dp)
    return out[..., :D] if pad else out


# ---------------------------------------------------------------------------
# single-chip flash attention (no ring): fused forward + custom backward
# ---------------------------------------------------------------------------


def _flash_kernel(causal, scale, bq, bk, nkb, t_real, with_lse=False):
    """One grid step computes one (bq, D) output block: fold the visiting
    k/v blocks with online softmax.  Outputs are written exactly once per
    grid step (blocked o spec) — no grid-revisited outputs, the construct
    this box's tunnel cannot tolerate.

    ``with_lse`` adds a per-row logsumexp output (the softmax normalizer,
    ``m + log l``) — the residual the backward kernels need to rebuild
    the probabilities tile by tile without ever storing them."""

    def kernel(q_ref, k_ref, v_ref, o_ref, *maybe_lse):
        iq = pl.program_id(1)
        # operands stay in the input dtype (bf16 MXU fast path); the
        # scale folds into the f32 scores, the softmax state is f32
        q = q_ref[0]  # (bq, D)
        q_pos = iq * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)

        def fold(j, carry):
            m, l, acc = carry
            kb = k_ref[0, pl.ds(j * bk, bk), :]
            vb = v_ref[0, pl.ds(j * bk, bk), :]
            s = jax.lax.dot_general(
                q, kb,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=_mxu_precision(q.dtype),
            ) * scale
            k_pos = j * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            mask = k_pos < t_real
            if causal:
                mask &= q_pos >= k_pos
            s = jnp.where(mask, s, _NEG)
            m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m - m_new)
            l_new = l * alpha + p.sum(axis=-1, keepdims=True)
            acc_new = acc * alpha + jax.lax.dot_general(
                p.astype(vb.dtype), vb,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=_mxu_precision(vb.dtype),
            )
            return m_new, l_new, acc_new

        init = (
            jnp.full((bq, 1), _NEG, jnp.float32),
            jnp.zeros((bq, 1), jnp.float32),
            jnp.zeros((bq, q.shape[-1]), jnp.float32),
        )
        # causal early exit: with bq == bk, q block iq only sees k blocks
        # 0..iq (dynamic trip count — Mosaic lowers it to a while loop)
        hi = jnp.minimum(iq + 1, nkb) if causal else nkb
        m, l, acc = lax.fori_loop(0, hi, fold, init)
        o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        if with_lse:
            # (bq, 1) sublane vector -> (bq,) lane vector: an explicit
            # relayout Mosaic supports; rows beyond t_real carry ~-1e30
            # and are masked out by the backward kernels
            maybe_lse[0][0, 0, 0] = (
                m + jnp.log(jnp.maximum(l, 1e-30))
            ).reshape(bq)

    return kernel


def _flash_block(T: int, dtype, block: int) -> int:
    """Block height for the flash kernels: a sublane multiple (f32 8 /
    bf16 16 / int8 32 — Mosaic rejects smaller VMEM tiles); short
    sequences round T UP to the sublane grid and pad, they don't shrink
    the tile below it.  Forward and backward must agree on this."""
    from ._common import sublanes_for

    sub = sublanes_for(dtype)
    return min(max(block // sub * sub, sub), (T + sub - 1) // sub * sub)


def _flash_struct(shape, dtype, *ops):
    """ShapeDtypeStruct inheriting the union of the operands' varying
    mesh axes — required for pallas_call outputs inside a
    ``check_vma=True`` shard_map (the sharded train steps)."""
    vma = frozenset().union(*(jax.typeof(o).vma for o in ops))
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _flash_kv_map(H: int, Hkv: int, blocked: bool = False):
    """Grid index -> flattened K/V head.  For grouped-query attention
    (Hkv < H) q head ``h`` reads kv head ``h // G`` — sharing happens in
    the BlockSpec index map, so the smaller K/V never get materialized
    at H heads anywhere (the whole point of GQA's cache savings).
    ``blocked=True`` returns the (head, block-i, 0) form for specs whose
    second dim follows the grid's block index."""
    if H == Hkv:
        head = lambda bh: bh  # noqa: E731
    else:
        G = H // Hkv
        head = lambda bh: (bh // H) * Hkv + (bh % H) // G  # noqa: E731
    if blocked:
        return lambda bh, i: (head(bh), i, 0)
    return lambda bh, i: (head(bh), 0, 0)


def _flash_fwd_impl(q, k, v, causal, block, interpret, with_lse):
    B, H, T, D = q.shape
    Hkv = k.shape[1]
    scale = 1.0 / (D ** 0.5)
    b = _flash_block(T, q.dtype, block)
    padT = (-T) % b
    padD = (-D) % LANES
    if padT or padD:
        padding = [(0, 0), (0, 0), (0, padT), (0, padD)]
        q, k, v = (jnp.pad(a, padding) for a in (q, k, v))
    Tp, Dp = T + padT, D + padD
    nq = nkb = Tp // b

    qf = q.reshape(B * H, Tp, Dp)
    kf = k.reshape(B * Hkv, Tp, Dp)
    vf = v.reshape(B * Hkv, Tp, Dp)
    kv_map = _flash_kv_map(H, Hkv)

    out_shape = [_flash_struct((B * H, Tp, Dp), q.dtype, q, k, v)]
    out_specs = [
        pl.BlockSpec((1, b, Dp), lambda bh, iq: (bh, iq, 0),
                     memory_space=pltpu.VMEM),
    ]
    if with_lse:
        # row-stat layout: (B*H, nq, 1, b) so the block (1, 1, 1, b) has
        # its last two dims EQUAL to the array's — the only tile shape
        # Mosaic accepts for a lane vector shorter than 128
        out_shape.append(
            _flash_struct((B * H, nq, 1, b), jnp.float32, q, k, v)
        )
        out_specs.append(
            pl.BlockSpec((1, 1, 1, b), lambda bh, iq: (bh, iq, 0, 0),
                         memory_space=pltpu.VMEM)
        )

    res = pl.pallas_call(
        _flash_kernel(causal, scale, b, b, nkb, T, with_lse=with_lse),
        grid=(B * H, nq),
        out_shape=out_shape,
        in_specs=[
            pl.BlockSpec((1, b, Dp), lambda bh, iq: (bh, iq, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp, Dp), kv_map, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp, Dp), kv_map, memory_space=pltpu.VMEM),
        ],
        out_specs=out_specs,
        interpret=default_interpret(interpret),
    )(qf, kf, vf)
    out = res[0].reshape(B, H, Tp, Dp)[:, :, :T, :D]
    if not with_lse:
        return out, None
    lse = res[1].reshape(B, H, Tp)[:, :, :T]  # (B*H, nq, 1, b) -> rows
    return out, lse


def _flash_bwd_dq_kernel(causal, scale, bq, bk, nkb, t_real):
    """dQ: grid step (bh, iq) owns one (bq, D) dq block, folding the k/v
    blocks it attended to.  Probabilities are rebuilt from the saved
    logsumexp (p = exp(s - lse)), never stored — the same FLOPs-for-HBM
    trade the forward makes [FlashAttention-2 backward split: the dq pass
    grids over q blocks so every output is written exactly once]."""

    def kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dq_ref):
        iq = pl.program_id(1)
        q = q_ref[0]
        do = do_ref[0]
        # (bq,) lane vectors -> (bq, 1) sublane vectors for row broadcast
        lse = lse_ref[0, 0, 0].reshape(bq, 1)
        delta = dl_ref[0, 0, 0].reshape(bq, 1)
        q_pos = iq * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)

        def fold(j, acc):
            kb = k_ref[0, pl.ds(j * bk, bk), :]
            vb = v_ref[0, pl.ds(j * bk, bk), :]
            s = lax.dot_general(
                q, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=_mxu_precision(q.dtype),
            ) * scale
            k_pos = j * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            mask = (k_pos < t_real) & (q_pos < t_real)
            if causal:
                mask &= q_pos >= k_pos
            # explicit where: padded q rows have lse ~ -1e30, where a bare
            # exp(s - lse) would resurrect them as p = 1
            p = jnp.where(mask, jnp.exp(s - lse), 0.0)
            dp = lax.dot_general(
                do, vb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=_mxu_precision(do.dtype),
            )
            ds = p * (dp - delta) * scale
            return acc + lax.dot_general(
                ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=_mxu_precision(kb.dtype),
            )

        hi = jnp.minimum(iq + 1, nkb) if causal else nkb
        acc = lax.fori_loop(
            0, hi, fold, jnp.zeros((bq, q.shape[-1]), jnp.float32)
        )
        dq_ref[0] = acc.astype(dq_ref.dtype)

    return kernel


def _flash_bwd_dkv_kernel(causal, scale, bq, bk, nq, t_real):
    """dK/dV: grid step (bh, jk) owns one (bk, D) dk + dv block pair,
    folding the q blocks that attended to it (causal: q blocks jk..nq-1
    — a dynamic lower bound, the mirror of the forward's early exit)."""

    def kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, dl_ref,
               dk_ref, dv_ref):
        jk = pl.program_id(1)
        kb = k_ref[0]
        vb = v_ref[0]
        D = kb.shape[-1]
        k_pos = jk * bk + lax.broadcasted_iota(jnp.int32, (bq, bk), 1)

        def fold(i, carry):
            dk, dv = carry
            qb = q_ref[0, pl.ds(i * bq, bq), :]
            dob = do_ref[0, pl.ds(i * bq, bq), :]
            lse = lse_ref[0, i, 0].reshape(bq, 1)
            delta = dl_ref[0, i, 0].reshape(bq, 1)
            s = lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=_mxu_precision(qb.dtype),
            ) * scale
            q_pos = i * bq + lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            mask = (k_pos < t_real) & (q_pos < t_real)
            if causal:
                mask &= q_pos >= k_pos
            p = jnp.where(mask, jnp.exp(s - lse), 0.0)
            dv = dv + lax.dot_general(
                p.astype(dob.dtype), dob, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=_mxu_precision(dob.dtype),
            )
            dp = lax.dot_general(
                dob, vb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=_mxu_precision(dob.dtype),
            )
            ds = p * (dp - delta) * scale
            dk = dk + lax.dot_general(
                ds.astype(qb.dtype), qb, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=_mxu_precision(qb.dtype),
            )
            return dk, dv

        lo = jnp.minimum(jk, nq) if causal else 0  # bq == bk
        dk, dv = lax.fori_loop(
            lo, nq, fold,
            (jnp.zeros((bk, D), jnp.float32),
             jnp.zeros((bk, D), jnp.float32)),
        )
        dk_ref[0] = dk.astype(dk_ref.dtype)
        dv_ref[0] = dv.astype(dv_ref.dtype)

    return kernel


def _flash_bwd_impl(q, k, v, o, lse, g, causal, block, interpret):
    B, H, T, D = q.shape
    Hkv = k.shape[1]
    G = H // Hkv
    scale = 1.0 / (D ** 0.5)
    b = _flash_block(T, q.dtype, block)
    padT = (-T) % b
    padD = (-D) % LANES
    # delta = rowsum(dO * O): the softmax-transpose correction, a cheap
    # fused elementwise+reduce XLA does well — no kernel needed
    delta = (g.astype(jnp.float32) * o.astype(jnp.float32)).sum(-1)
    if padT or padD:
        padding = [(0, 0), (0, 0), (0, padT), (0, padD)]
        q, k, v, g = (jnp.pad(a, padding) for a in (q, k, v, g))
    if padT:
        rows = [(0, 0), (0, 0), (0, padT)]
        lse = jnp.pad(lse, rows, constant_values=_NEG)
        delta = jnp.pad(delta, rows)
    Tp, Dp = T + padT, D + padD
    nq = nkb = Tp // b

    qf = q.reshape(B * H, Tp, Dp)
    kf = k.reshape(B * Hkv, Tp, Dp)
    vf = v.reshape(B * Hkv, Tp, Dp)
    dof = g.reshape(B * H, Tp, Dp)
    # row-stat layout (see _flash_fwd_impl): block last-two dims == array
    lsef = lse.reshape(B * H, nq, 1, b)
    dlf = delta.reshape(B * H, nq, 1, b)
    kv_whole = pl.BlockSpec((1, Tp, Dp), _flash_kv_map(H, Hkv),
                            memory_space=pltpu.VMEM)
    kv_blk = pl.BlockSpec((1, b, Dp), _flash_kv_map(H, Hkv, blocked=True),
                          memory_space=pltpu.VMEM)

    blk = pl.BlockSpec((1, b, Dp), lambda bh, i: (bh, i, 0),
                       memory_space=pltpu.VMEM)
    whole = pl.BlockSpec((1, Tp, Dp), lambda bh, i: (bh, 0, 0),
                         memory_space=pltpu.VMEM)
    rows_blk = pl.BlockSpec((1, 1, 1, b), lambda bh, i: (bh, i, 0, 0),
                            memory_space=pltpu.VMEM)
    rows_whole = pl.BlockSpec((1, nq, 1, b), lambda bh, i: (bh, 0, 0, 0),
                              memory_space=pltpu.VMEM)

    grad_struct = _flash_struct((B * H, Tp, Dp), q.dtype, q, k, v, g)
    dq = pl.pallas_call(
        _flash_bwd_dq_kernel(causal, scale, b, b, nkb, T),
        grid=(B * H, nq),
        out_shape=grad_struct,
        in_specs=[blk, kv_whole, kv_whole, blk, rows_blk, rows_blk],
        out_specs=blk,
        interpret=default_interpret(interpret),
    )(qf, kf, vf, dof, lsef, dlf)

    # dk/dv come out PER Q-HEAD (every output block still written exactly
    # once — adding a group grid dim would revisit them); the group sum
    # is one cheap XLA reduction after the kernel
    dk, dv = pl.pallas_call(
        _flash_bwd_dkv_kernel(causal, scale, b, b, nq, T),
        grid=(B * H, nkb),
        out_shape=[grad_struct] * 2,
        in_specs=[kv_blk, kv_blk, whole, whole, rows_whole, rows_whole],
        out_specs=[blk, blk],
        interpret=default_interpret(interpret),
    )(kf, vf, qf, dof, lsef, dlf)

    dq = dq.reshape(B, H, Tp, Dp)[:, :, :T, :D]
    def group_sum(a):
        a = a.reshape(B, Hkv, G, Tp, Dp)[:, :, :, :T, :D]
        if G == 1:
            return a[:, :, 0]
        return a.astype(jnp.float32).sum(2).astype(k.dtype)
    return dq, group_sum(dk), group_sum(dv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_vjp(q, k, v, causal, block, interpret):
    out, _ = _flash_fwd_impl(q, k, v, causal, block, interpret,
                             with_lse=False)
    return out


def _flash_vjp_fwd(q, k, v, causal, block, interpret):
    out, lse = _flash_fwd_impl(q, k, v, causal, block, interpret,
                               with_lse=True)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, block, interpret, res, g):
    q, k, v, o, lse = res
    return _flash_bwd_impl(q, k, v, o, lse, g, causal, block, interpret)


_flash_vjp.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    *,
    block: int = 512,
    interpret: InterpretArg = None,
) -> jax.Array:
    """Local (single-chip) fused attention: ``(B, H, T, D) -> same`` with
    the (T, T) score matrix never leaving VMEM — the kernel-owned form of
    ``ops.attention.blockwise_attention``, and like it fully trainable:
    a ``custom_vjp`` pairs the forward (which saves only o + per-row
    logsumexp) with two backward Pallas kernels (dq; dk+dv) that rebuild
    the probability tiles on the fly.  Every output block is written
    exactly once per grid step across all three kernels (no
    grid-revisited outputs, the construct this box's tunnel cannot
    tolerate).

    Grouped-query attention comes free: pass k/v with FEWER heads
    (``(B, Hkv, T, D)``, ``H % Hkv == 0``) and q head ``h`` reads kv head
    ``h // (H // Hkv)`` through the BlockSpec index map — the smaller K/V
    are never expanded to H heads anywhere (fwd or bwd).

    K/V live whole in VMEM per (batch*head) grid step — sized for
    serving/training sequence lengths (T <= ~8K at 128 lanes); the ring
    kernel covers longer sequences across chips.

    ``block=512`` is the measured optimum on v5e at T=4096: vs 256 the
    forward runs 2.1x faster (40.7 vs 19.6 TFLOPs) and the full T=4096
    train step gains 6.9 MFU points (62.1% -> 69.0%, A/B on the bench's
    own step); 1024 regresses (VMEM pressure).  Short sequences clamp
    the block to T via ``_flash_block``."""
    if k.shape != v.shape:
        raise ValueError(f"k/v shapes must match, got {k.shape}/{v.shape}")
    B, H, T, D = q.shape
    Bk, Hkv, Tk, Dk = k.shape
    if (Bk, Tk, Dk) != (B, T, D) or Hkv <= 0 or H % Hkv:
        raise ValueError(
            f"q/k shapes must match outside the head dim and q heads must "
            f"be a multiple of kv heads, got {q.shape}/{k.shape}"
        )
    if k.dtype != q.dtype or v.dtype != q.dtype:
        raise ValueError(
            f"q/k/v dtypes must match (tiles and accumulators are typed "
            f"from q), got {q.dtype}/{k.dtype}/{v.dtype}"
        )
    require_mosaic_dtypes(default_interpret(interpret), "flash attention",
                          q.dtype)
    return _flash_vjp(q, k, v, causal, block, interpret)
