"""Ring collectives as Pallas TPU kernels — the dataplane hot loop on ICI.

The reference's headline allreduce is a *segmented ring reduce-scatter +
ring allgather* the firmware drives through the DMA-mover: per hop it
issues a strided read, an RX-buffer seek for the incoming fragment, a fused
reduce, and a packetizer command to the next rank, releasing RX buffers on
ack (/root/reference/kernels/cclo/fw/sw_apps/ccl_offload_control/src/
ccl_offload_control.c:1888-2071; dma_mover.cpp:433-703).  This module is
that machine re-built for TPU hardware: one Pallas kernel per collective in
which every hop is a Mosaic **remote DMA** to the ring neighbor over ICI,
segments pipeline the wire against the VPU reduce, and a slot-ack protocol
(regular semaphores signalled back to the sender) plays the role of the
eager RX-buffer release path.

All entry points run *inside* ``shard_map`` over a 1-D mesh axis whose
order matches the devices' ICI ring.  ``num_segments`` is the reference's
segmentation tuning knob: each ring hop is split into that many
independently-DMA'd segments so hop ``s``'s wire time overlaps hop
``s``'s reduce time.  On non-TPU backends the same kernels execute under
the Pallas TPU interpreter (see ``_common``), which is also how the test
tier runs them — optionally with the interpreter's vector-clock race
detector enabled.
"""

from __future__ import annotations

import jax

from ...compat import install as _compat_install

_compat_install()  # legacy-jax shims (shard_map kwargs, lax.axis_size)
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...constants import ReduceFunction
from ._common import (
    LANES,
    InterpretArg,
    ack_gate,
    ack_release,
    default_interpret,
    require_mosaic_dtypes,
    neighbor_barrier,
    pack_lanes,
    sublanes_for,
)

_OPS = {
    ReduceFunction.SUM: jnp.add,
    ReduceFunction.MAX: jnp.maximum,
}


def _pack_ring(x: jax.Array, size: int, num_segments: int,
               wire_dtype=None):
    """Flatten + pad to (size * num_segments * sublane-aligned segB, LANES).

    When a narrower wire dtype rides the comm buffers, segment tiles must
    satisfy BOTH dtypes' sublane minimums (bf16 needs 16 where f32 needs
    8) or the compiled wire buffers violate Mosaic tile alignment."""
    sub = sublanes_for(x.dtype)
    if wire_dtype is not None:
        sub = max(sub, sublanes_for(wire_dtype))
    return pack_lanes(x, min_rows=size * num_segments * sub)


def _neighbors(axis_name: str, size: int):
    me = lax.axis_index(axis_name)
    nxt = jnp.where(me + 1 == size, 0, me + 1)
    prv = jnp.where(me == 0, size - 1, me - 1)
    return me, nxt, prv


def hop_source(me, hop, size):
    """Rank whose block rank ``me`` holds after ``hop`` ring hops (the
    FUSED_ATTN_HOP peer word carries the hop OFFSET, not an absolute
    rank — slots are encoded once globally, so the word is SPMD-uniform
    and each rank derives its source here, on device or host).  Works
    for python ints and traced values alike."""
    return (me - hop + size) % size


def _ring_barrier(nxt, prv):
    neighbor_barrier(nxt, prv)


def _hop(dst_ref, src_ref, send_ref, recv_ref, ack_ref, dst_dev, hop):
    """One segment of one ring hop: ack-gated remote DMA of ``src_ref``
    into ``dst_ref`` on device ``dst_dev`` (a comm slot there).  All refs
    arrive fully indexed.  Returns the descriptor to wait on.  Ack
    protocol = the reference's RX-buffer release: a slot is rewritten two
    hops later only after its consumer signalled it free."""
    ack_gate(ack_ref, hop)
    rdma = pltpu.make_async_remote_copy(
        src_ref=src_ref,
        dst_ref=dst_ref,
        send_sem=send_ref,
        recv_sem=recv_ref,
        device_id=dst_dev,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    rdma.start()
    return rdma


def _release(ack_ref, ups, hop, total_hops):
    """Tell the sender (upstream rank) its slot is consumed — unless no
    future hop will reuse it (semaphores drain to zero by kernel end)."""
    ack_release(ack_ref, hop, total_hops, ups)


def _scratch(size, num_segments, seg_rows, dtype):
    return [
        pltpu.VMEM((2, num_segments, seg_rows, LANES), dtype),  # comm slots
        pltpu.SemaphoreType.DMA((2, num_segments)),  # send
        pltpu.SemaphoreType.DMA((2, num_segments)),  # recv
        pltpu.SemaphoreType.REGULAR((2, num_segments)),  # slot acks
    ]


def _allreduce_kernel(axis_name, size, num_segments, op, ndirs=1,
                      wire_dtype=None):
    """Segmented ring allreduce over 1 or 2 direction lanes.

    ``ndirs=2`` is the bidirectional ring (pallas_guide 'Bi-directional
    Ring'): the operand's two halves travel in opposite directions around
    the ring simultaneously, using both ICI links of each neighbor pair —
    2x the usable ring bandwidth.  Each direction lane is a complete,
    independent instance of the slot-ack protocol (own comm slots,
    semaphores, accumulator); the hop loop interleaves them so both wires
    are in flight before either fold begins."""
    total_hops = 2 * (size - 1)
    compressed = wire_dtype is not None

    def kernel(x_ref, o_ref, acc, comm, *rest):
        # rest = (stage, send_sem, recv_sem, ack_sem) when compressed,
        #        (send_sem, recv_sem, ack_sem) otherwise
        if compressed:
            stage, send_sem, recv_sem, ack_sem = rest
        else:
            stage = acc  # send directly from the accumulator
            send_sem, recv_sem, ack_sem = rest
        me, nxt, prv = _neighbors(axis_name, size)
        S = num_segments
        segB = comm.shape[3]
        B = S * segB
        H = size * B  # rows per direction half

        def up(v):
            # wire -> accumulate dtype (the hp_compression decompress lane)
            return v.astype(acc.dtype) if compressed else v

        # (destination, upstream, ring orientation sign) per lane
        dirs = [(nxt, prv, 1)]
        if ndirs == 2:
            dirs.append((prv, nxt, -1))

        def xseg(d, blk, j):
            start = d * H + jnp.mod(blk, size) * B + j * segB
            return x_ref[pl.ds(start, segB), :]

        _ring_barrier(nxt, prv)

        # --- ring reduce-scatter: hops 1 .. P-1 --------------------------
        for d, (_, _, sg) in enumerate(dirs):
            for j in range(S):
                acc[d, j] = xseg(d, me - sg, j)
        for s in range(1, size):
            slot = s % 2
            rdmas = {}
            for d, (dst, ups, _) in enumerate(dirs):
                for j in range(S):
                    if compressed:  # narrow onto the wire (compress lane)
                        stage[d, j] = acc[d, j].astype(stage.dtype)
                    rdmas[d, j] = _hop(
                        comm.at[d, slot, j], stage.at[d, j],
                        send_sem.at[d, slot, j], recv_sem.at[d, slot, j],
                        ack_sem.at[d, slot, j], dst, s,
                    )
            for d, (_, ups, sg) in enumerate(dirs):
                for j in range(S):
                    rdmas[d, j].wait_recv()  # upstream partial landed
                    rdmas[d, j].wait_send()  # our stage is free to rewrite
                    acc[d, j] = op(
                        up(comm[d, slot, j]), xseg(d, me - sg * (1 + s), j)
                    )
                    _release(ack_sem.at[d, slot, j], ups, s, total_hops)

        # acc now holds the fully-reduced block ``me`` of each half
        for d in range(len(dirs)):
            for j in range(S):
                o_ref[pl.ds(d * H + me * B + j * segB, segB), :] = acc[d, j]

        # --- ring allgather: hops P .. 2P-2 ------------------------------
        for t in range(1, size):
            h = size - 1 + t
            slot = h % 2
            rdmas = {}
            for d, (dst, ups, _) in enumerate(dirs):
                for j in range(S):
                    if compressed:
                        stage[d, j] = acc[d, j].astype(stage.dtype)
                    rdmas[d, j] = _hop(
                        comm.at[d, slot, j], stage.at[d, j],
                        send_sem.at[d, slot, j], recv_sem.at[d, slot, j],
                        ack_sem.at[d, slot, j], dst, h,
                    )
            for d, (_, ups, sg) in enumerate(dirs):
                origin = jnp.mod(me - sg * t, size)
                for j in range(S):
                    rdmas[d, j].wait_recv()
                    rdmas[d, j].wait_send()
                    o_ref[pl.ds(d * H + origin * B + j * segB, segB), :] = (
                        up(comm[d, slot, j]).astype(o_ref.dtype)
                    )
                    acc[d, j] = up(comm[d, slot, j])  # relay on the next hop
                    _release(ack_sem.at[d, slot, j], ups, h, total_hops)

    return kernel


def _reduce_scatter_kernel(axis_name, size, num_segments, op):
    total_hops = size - 1

    def kernel(x_ref, o_ref, comm, send_sem, recv_sem, ack_sem):
        me, nxt, prv = _neighbors(axis_name, size)
        S = num_segments
        segB = comm.shape[2]
        B = S * segB

        def xseg(blk, j):
            start = jnp.mod(blk, size) * B + j * segB
            return x_ref[pl.ds(start, segB), :]

        _ring_barrier(nxt, prv)
        for j in range(S):
            o_ref[pl.ds(j * segB, segB), :] = xseg(me - 1, j)
        for s in range(1, size):
            slot = s % 2
            rdmas = [
                _hop(comm.at[slot, j], o_ref.at[pl.ds(j * segB, segB), :],
                     send_sem.at[slot, j], recv_sem.at[slot, j],
                     ack_sem.at[slot, j], nxt, s)
                for j in range(S)
            ]
            for j in range(S):
                rdmas[j].wait_recv()
                rdmas[j].wait_send()
                o_ref[pl.ds(j * segB, segB), :] = op(
                    comm[slot, j], xseg(me - 1 - s, j)
                )
                _release(ack_sem.at[slot, j], prv, s, total_hops)

    return kernel


def relay_allgather_hops(dst_write, carry, comm, send_sem, recv_sem,
                         ack_sem, me, nxt, prv, size):
    """The store-and-relay ring allgather hop loop (ref
    ccl_offload_control.c:1402-1500), factored out so the allgather
    kernel AND the command-ring sequencer (``cmdring``) drive the same
    machine: ``carry[j]`` must be pre-seeded with this rank's own block
    segments; ``dst_write(origin, j, data)`` places each arriving
    block's segment ``j`` (``origin`` = the block's home rank, traced).
    Segment count derives from ``carry``'s leading dim; semaphores drain
    to zero by loop end (the slot-ack release discipline)."""
    S = carry.shape[0]
    total_hops = size - 1
    for t in range(1, size):
        slot = t % 2
        rdmas = [
            _hop(comm.at[slot, j], carry.at[j],
                 send_sem.at[slot, j], recv_sem.at[slot, j],
                 ack_sem.at[slot, j], nxt, t)
            for j in range(S)
        ]
        origin = jnp.mod(me - t, size)
        for j in range(S):
            rdmas[j].wait_recv()
            rdmas[j].wait_send()
            dst_write(origin, j, comm[slot, j])
            carry[j] = comm[slot, j]
            _release(ack_sem.at[slot, j], prv, t, total_hops)


def _allgather_kernel(axis_name, size, num_segments):
    def kernel(x_ref, o_ref, carry, comm, send_sem, recv_sem, ack_sem):
        me, nxt, prv = _neighbors(axis_name, size)
        S = num_segments
        segB = comm.shape[2]
        B = S * segB

        _ring_barrier(nxt, prv)
        for j in range(S):
            carry[j] = x_ref[pl.ds(j * segB, segB), :]
            o_ref[pl.ds(me * B + j * segB, segB), :] = carry[j]

        def place(origin, j, data):
            o_ref[pl.ds(origin * B + j * segB, segB), :] = data

        relay_allgather_hops(
            place, carry, comm, send_sem, recv_sem, ack_sem, me, nxt, prv,
            size,
        )

    return kernel


def _call(kernel, x, out_rows, scratch, collective_id, interpret):
    interp = default_interpret(interpret)
    # no XLA reroute here: these are remote-DMA kernels, not math — an
    # abort-the-session compile failure becomes a usable error
    require_mosaic_dtypes(interp, "ring collective", x.dtype)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((out_rows, LANES), x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=scratch,
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=interp,
    )(x)


def ring_allreduce(
    x: jax.Array,
    axis_name: str,
    function: ReduceFunction = ReduceFunction.SUM,
    num_segments: int = 1,
    *,
    bidirectional: bool = False,
    wire_dtype=None,
    collective_id: int = 0,
    interpret: InterpretArg = None,
) -> jax.Array:
    """Segmented-ring allreduce (reduce-scatter + allgather) as one Pallas
    kernel: 2(P-1) neighbor remote-DMA hops on ICI (ref allreduce,
    ccl_offload_control.c:1888-2071).

    ``bidirectional=True`` splits the operand in half and runs the two
    halves around the ring in opposite directions simultaneously — both
    ICI links per neighbor pair carry payload, doubling usable ring
    bandwidth (beyond the reference, whose eager ring is one-directional).

    ``wire_dtype`` (e.g. ``jnp.bfloat16``) narrows every hop's payload on
    the wire while accumulating in the operand dtype — the ETH_COMPRESSED
    / hp_compression composition executed inside the kernel: compress lane
    before the DMA, decompress after, half the ICI bytes.
    """
    size = lax.axis_size(axis_name)
    if size == 1:
        return x
    op = _OPS[function]
    ndirs = 2 if bidirectional else 1
    wire = jnp.dtype(wire_dtype) if wire_dtype is not None else None
    if wire is not None and wire == x.dtype:
        wire = None  # no-op compression
    require_mosaic_dtypes(
        default_interpret(interpret), "ring allreduce (wire_dtype)", wire
    )
    xp, n = _pack_ring(x, ndirs * size, num_segments, wire)
    rows = xp.shape[0]
    seg_rows = rows // (ndirs * size * num_segments)
    S = num_segments
    comm_dtype = wire if wire is not None else x.dtype
    scratch = [
        pltpu.VMEM((ndirs, S, seg_rows, LANES), x.dtype),  # accumulators
        pltpu.VMEM((ndirs, 2, S, seg_rows, LANES), comm_dtype),  # comm slots
    ]
    if wire is not None:
        scratch.append(
            pltpu.VMEM((ndirs, S, seg_rows, LANES), wire)  # send staging
        )
    scratch += [
        pltpu.SemaphoreType.DMA((ndirs, 2, S)),  # send
        pltpu.SemaphoreType.DMA((ndirs, 2, S)),  # recv
        pltpu.SemaphoreType.REGULAR((ndirs, 2, S)),  # slot acks
    ]
    out = _call(
        _allreduce_kernel(
            axis_name, size, num_segments, op, ndirs, wire
        ),
        xp, rows, scratch, collective_id, interpret,
    )
    return out.reshape(-1)[:n].reshape(x.shape)


def ring_reduce_scatter(
    x: jax.Array,
    axis_name: str,
    function: ReduceFunction = ReduceFunction.SUM,
    num_segments: int = 1,
    *,
    collective_id: int = 0,
    interpret: InterpretArg = None,
) -> jax.Array:
    """Ring reduce-scatter: P-1 fused recv-reduce-send hops (ref
    ccl_offload_control.c:1782-1851).  Returns rank ``i``'s reduced block
    of the (padded) operand, flattened to (block_rows, 128)."""
    size = lax.axis_size(axis_name)
    op = _OPS[function]
    xp, _ = _pack_ring(x, size, num_segments)
    rows = xp.shape[0]
    if size == 1:
        return xp
    seg_rows = rows // (size * num_segments)
    scratch = _scratch(size, num_segments, seg_rows, x.dtype)
    return _call(
        _reduce_scatter_kernel(axis_name, size, num_segments, op),
        xp, rows // size, scratch, collective_id, interpret,
    )


def ring_allgather(
    x: jax.Array,
    axis_name: str,
    num_segments: int = 1,
    *,
    collective_id: int = 0,
    interpret: InterpretArg = None,
) -> jax.Array:
    """Ring allgather: store-and-relay around the ring (ref
    ccl_offload_control.c:1402-1500).  ``x`` is this rank's block; returns
    all blocks concatenated along the leading axis."""
    size = lax.axis_size(axis_name)
    if size == 1:
        return x
    xp, n = _pack_ring(x, 1, num_segments)
    rows = xp.shape[0]
    seg_rows = rows // num_segments
    scratch = [pltpu.VMEM((num_segments, seg_rows, LANES), x.dtype)]
    scratch += _scratch(size, num_segments, seg_rows, x.dtype)
    out = _call(
        _allgather_kernel(axis_name, size, num_segments),
        xp, rows * size, scratch, collective_id, interpret,
    )
    blocks = out.reshape(size, -1)[:, :n]
    return blocks.reshape((size * x.shape[0],) + x.shape[1:])


def int8_allreduce(
    x: jax.Array,
    axis_name: str,
    num_segments: int = 1,
    *,
    collective_id: int = 0,
    scale_collective_id: int = 4,
    interpret: InterpretArg = None,
) -> jax.Array:
    """Allreduce with blockwise-int8 wire compression on the Pallas ring
    tier — the ``hp_compression`` role at its narrowest lane.

    A plain dtype cast (the ``wire_dtype`` path of :func:`ring_allreduce`)
    cannot express int8: blockwise quantization needs a per-tile scale
    riding with the payload.  So the composition is quantize-once /
    gather / dequantize-reduce: each rank quantizes its full operand with
    the Pallas quant kernel (one fp32 scale per ~32 KiB tile), the int8
    payload AND the scale vector ride the Pallas ring allgather
    (store-and-relay remote DMAs), and every rank dequantizes each peer
    block with the Pallas dequant kernel and reduces locally.

    Wire cost: ``(P-1) * n`` int8 bytes per rank (plus ~n/8192 scale
    bytes) versus the f32 ring's ``2(P-1)/P * 4n`` — ~2x fewer wire
    bytes at P=4 and, unlike a reduce-scatter ring in int8, the payload
    is quantized exactly ONCE, so the error bound is the sum of each
    rank's own tile scales (asserted in the e2e test), not a per-hop
    requantization cascade.

    CONSUMES TWO collective ids: ``collective_id`` for the payload ring
    and ``scale_collective_id`` for the scale ring (the module
    namespace holds 0=ring, 1=put, 2=attention, 3=alltoall, 4=this
    scale leg) — compose with other collective kernels accordingly.
    """
    from .compression import dequantize_int8, quantize_int8

    size = lax.axis_size(axis_name)
    if size == 1:
        return x
    values, scales, n = quantize_int8(x, interpret=interpret)
    rows = values.shape[0]
    nblk = scales.shape[0]
    # two ring kernels in one program get DISTINCT collective ids so
    # their barrier semaphores can never alias (id-namespace hygiene;
    # note the size=8 interpreter slowness investigated alongside this
    # turned out to be the single-core busy-spin convoy below, not id
    # aliasing — distinct ids are kept as correct composition anyway)
    all_v = ring_allgather(
        values.reshape(-1), axis_name, num_segments,
        collective_id=collective_id, interpret=interpret,
    ).reshape(size, rows, LANES)
    all_s = ring_allgather(
        scales.reshape(-1), axis_name,
        collective_id=scale_collective_id, interpret=interpret,
    ).reshape(size, nblk, 1)
    # ONE batched dequant kernel over all ranks' blocks (the per-tile
    # scale arithmetic is position-independent), then trim each rank's
    # lane padding and reduce — P kernel launches would otherwise stack
    # up on the collective hot path
    flat = dequantize_int8(
        all_v.reshape(size * rows, LANES),
        all_s.reshape(size * nblk, 1),
        size * rows * LANES, (size, rows * LANES), jnp.float32,
        interpret=interpret,
    )
    acc = flat[:, :n].sum(axis=0)
    return acc.reshape(x.shape).astype(x.dtype)
