"""Pallas TPU kernel tier: the reference's hardware dataplane as real
TPU kernels.

* ``combine`` — the reduce_ops arithmetic plugin (fused elementwise
  SUM/MAX with optional result-lane cast).
* ``compression`` — the hp_compression plugin (dtype casts incl.
  stochastic rounding, plus blockwise int8 wire quantization).
* ``ring`` — the firmware's segmented ring collectives as single Pallas
  kernels whose hops are Mosaic remote DMAs over ICI, with slot-ack flow
  control (the RX-buffer release protocol).
* ``cmdring`` — the device-resident command ring (the CCLO run-loop
  analog): host-side slot encoder + the sequencer program that decodes
  slots on device and executes a whole refill window under one
  dispatch.

On non-TPU backends every kernel runs under the Pallas TPU interpreter so
the CI tier exercises the identical kernel code (see
``_common.default_interpret``).
"""

from . import (  # noqa: F401
    alltoall,
    attention,
    cmdring,
    compression,
    put,
    ring,
    rooted,
)
from ._common import default_interpret, pack_lanes, unpack_lanes  # noqa: F401
from .attention import flash_attention  # noqa: F401
from .alltoall import alltoall as alltoall_kernel  # noqa: F401
from .combine import combine  # noqa: F401
from .compression import cast, dequantize_int8, quantize_int8  # noqa: F401
from .put import fused_shift  # noqa: F401
from .ring import (  # noqa: F401
    int8_allreduce,
    ring_allgather,
    ring_allreduce,
    ring_reduce_scatter,
)
from .rooted import (  # noqa: F401
    ring_bcast,
    ring_gather,
    ring_reduce,
    ring_scatter,
)
