"""Fused compute + remote put: device-initiated communication in one
kernel.

The reference lets FPGA compute kernels command the collective engine with
no host in the data path: ``vadd_put`` reads fp32, adds a constant, streams
the result into the CCLO and issues ``stream_put`` to a remote rank
(/root/reference/kernels/plugins/vadd_put/vadd_put.cpp:25-100, via the HLS
bindings driver/hls/accl_hls.h:277-298).  The TPU-native form of "the
kernel owns the wire" is a Pallas kernel that computes in VMEM and then
issues the Mosaic remote DMA itself — compute and communication fused in
one Mosaic program, no separate collective op, no host round-trip.

``fused_shift`` is the SPMD shape of that flow: every rank computes
``compute(x)`` and puts the result into the output buffer of the rank
``distance`` away on the ring (the reference's tag-matched ``stream_put``
to a chosen peer, arranged symmetrically so SPMD semaphore accounting is
static).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax

from ...compat import install as _compat_install

_compat_install()  # legacy-jax shims (shard_map kwargs, lax.axis_size)
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ._common import (
    LANES,
    InterpretArg,
    default_interpret,
    require_mosaic_dtypes,
    neighbor_barrier,
    pack_lanes,
)


def remote_block_put(src_ref, dst_ref, send_sem, recv_sem, dst_dev):
    """One device-initiated block put: remote-DMA ``src_ref`` into
    ``dst_ref`` on ``dst_dev`` and block until both sides drained — the
    ``stream_put`` primitive factored out of :func:`fused_shift` so
    other kernels (the command-ring sequencer's two-rank exchange) can
    compose it.  The caller owns the pre-put barrier (the remote ref
    must exist before data lands in it)."""
    rdma = pltpu.make_async_remote_copy(
        src_ref=src_ref,
        dst_ref=dst_ref,
        send_sem=send_sem,
        recv_sem=recv_sem,
        device_id=dst_dev,
        device_id_type=pltpu.DeviceIdType.LOGICAL,
    )
    rdma.start()
    # acclint: allow[unbounded-wait] Mosaic-traced DMA semaphore wait
    # inside the kernel: Pallas remote copies have no timeout form;
    # the host-side gang watchdog bounds the whole program instead
    rdma.wait()


def _kernel(axis_name: str, size: int, distance: int, compute):
    def kernel(x_ref, o_ref, y, send_sem, recv_sem):
        me = lax.axis_index(axis_name)
        dst = jnp.mod(me + distance, size)
        src = jnp.mod(me - distance, size)

        # compute phase: the "vadd" half, any VMEM->VMEM function
        y[:] = compute(x_ref[:])

        # put phase: the "stream_put" half — this kernel, not the host and
        # not a collective op, initiates the wire transfer
        neighbor_barrier(dst, src)
        remote_block_put(y, o_ref, send_sem, recv_sem, dst)

    return kernel


def fused_shift(
    x: jax.Array,
    axis_name: str,
    distance: int = 1,
    compute: Optional[Callable[[jax.Array], jax.Array]] = None,
    *,
    collective_id: int = 1,
    interpret: InterpretArg = None,
) -> jax.Array:
    """Compute ``compute(x)`` on-chip and put the result into the output of
    rank ``(me + distance) % size``; returns what rank ``(me - distance)``
    put here.  Runs inside ``shard_map`` over a 1-D mesh axis.

    This is ``vadd_put`` in one Mosaic program: compute result never
    returns to the host or to XLA before crossing ICI.
    """
    size = lax.axis_size(axis_name)
    compute = compute if compute is not None else (lambda v: v)
    if size == 1:
        xp, n = pack_lanes(x)
        return compute(xp).reshape(-1)[:n].reshape(x.shape)
    interp = default_interpret(interpret)
    require_mosaic_dtypes(interp, "fused-put", x.dtype)
    xp, n = pack_lanes(x)
    rows = xp.shape[0]
    out = pl.pallas_call(
        _kernel(axis_name, size, distance, compute),
        out_shape=jax.ShapeDtypeStruct((rows, LANES), x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((rows, LANES), x.dtype),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
        compiler_params=pltpu.CompilerParams(
            has_side_effects=True, collective_id=collective_id
        ),
        interpret=interp,
    )(xp)
    return out.reshape(-1)[:n].reshape(x.shape)
