"""Fused elementwise combine kernel — the ``reduce_ops`` plugin as a TPU
kernel.

The reference's arithmetic plugin is a SIMD unit on 512-bit stream words
with a TDEST-selected (dtype x function) lane table
(/root/reference/kernels/plugins/reduce_ops/reduce_ops.cpp:88-97, SUM/MAX
over {fp32, fp64, i32, i64, fp16}).  Here the same role is a Pallas grid
kernel: operands stream HBM->VMEM in (rows, 128) tiles (the grid pipeline
double-buffers the DMAs), the VPU applies the reduction, and the result
streams back — optionally cast to a different output dtype, which fuses the
``hp_compression`` result lane into the same pass.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ...constants import ReduceFunction
from ._common import (
    LANES,
    InterpretArg,
    block_rows,
    default_interpret,
    mosaic_rejects,
    pack_lanes,
    unpack_lanes,
)

_OPS = {
    ReduceFunction.SUM: jnp.add,
    ReduceFunction.MAX: jnp.maximum,
}


def _kernel(op, out_dtype):
    def kernel(a_ref, b_ref, o_ref):
        o_ref[:] = op(a_ref[:], b_ref[:]).astype(out_dtype)

    return kernel


def combine(
    a: jax.Array,
    b: jax.Array,
    function: ReduceFunction = ReduceFunction.SUM,
    out_dtype: Optional[jnp.dtype] = None,
    *,
    accumulate: bool = False,
    interpret: InterpretArg = None,
) -> jax.Array:
    """``out = function(a, b)`` on device — ref ``ACCL::combine``
    (driver/xrt/src/accl.cpp) executed by the reduce_ops lane.

    Accepts any shape; internally tiles to (rows, 128).  ``out_dtype``
    fuses the result-lane compression cast.

    ``accumulate=True`` is the in-place form (``a <- f(a, b)``): the output
    aliases the PACKED operand's HBM (``input_output_aliases``), so the
    result lands in the pages just read — on v5e this roughly doubles the
    streaming rate versus a third distinct stream (measured ~830 vs ~410
    GB/s) and beats XLA's fused elementwise (~700).  The alias is on the
    lane-packed intermediate: when ``a`` is already lane-packed
    ((rows, 128), no padding) and the call runs under jit, ``a`` itself is
    donated and invalidated like the reference's in-place device BOs;
    otherwise ``pack_lanes`` reshapes/pads into a copy and the caller's
    array is left untouched.
    """
    if a.shape != b.shape or a.dtype != b.dtype:
        raise ValueError("combine operands must match in shape and dtype")
    try:
        op = _OPS[function]
    except KeyError:
        raise ValueError(f"unsupported reduce function {function}") from None
    out_dtype = jnp.dtype(out_dtype or a.dtype)
    if accumulate and out_dtype != a.dtype:
        raise ValueError("accumulate=True requires out_dtype == a.dtype")
    interp = default_interpret(interpret)
    if mosaic_rejects(interp, a.dtype, out_dtype):
        # fp16 combines (a reduce_ops lane dtype, reduce_ops.cpp:88-97)
        # can't lower through Mosaic — same VPU math via XLA instead
        # (the in-place aliasing perf contract doesn't apply to f16)
        return op(a, b).astype(out_dtype)

    ap, n = pack_lanes(a)
    bp, _ = pack_lanes(b)
    rows = ap.shape[0]
    # block height by the WIDEST stream's dtype: ~1 MiB blocks, so the
    # 3 streams x 2 pipeline buffers stay well under VMEM even for f64
    # operands with a narrow fused output cast
    widest = max(jnp.dtype(a.dtype).itemsize, out_dtype.itemsize)
    br = block_rows(rows, want=max(512, 2048 * 4 // widest))
    grid = (rows // br,)
    spec = pl.BlockSpec((br, LANES), lambda i: (i, 0), memory_space=pltpu.VMEM)

    out = pl.pallas_call(
        _kernel(op, out_dtype),
        out_shape=jax.ShapeDtypeStruct((rows, LANES), out_dtype),
        grid=grid,
        in_specs=[spec, spec],
        out_specs=spec,
        input_output_aliases={0: 0} if accumulate else {},
        interpret=interp,
    )(ap, bp)
    return unpack_lanes(out, n, a.shape)
