"""Host-level drivers: global arrays in, jitted SPMD collectives out.

The convention mirrors the test harness of the reference (per-rank operand
buffers): operands are *stacked* along a leading rank axis — ``stacked[r]``
is rank r's contribution — and results come back stacked the same way.
Under the hood each call builds (and caches) one jitted ``shard_map``
program over the mesh; on TPU the transfers ride ICI.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map  # type: ignore

from ..constants import ReduceFunction
from . import collectives, pallas, ring

AXIS = "ranks"


def make_mesh(n: Optional[int] = None, axis: str = AXIS) -> Mesh:
    devs = jax.devices()
    n = n or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    return Mesh(devs[:n], (axis,))


def _smap(mesh: Mesh, fn, in_spec, out_spec, donate: bool = False):
    return jax.jit(
        shard_map(
            fn,
            mesh=mesh,
            in_specs=in_spec,
            out_specs=out_spec,
            check_vma=False,
        ),
        # donation: in-place collectives (bcast writes its own operand) hand
        # their operand's HBM to XLA, the jax analog of the reference's
        # in-place device BOs
        donate_argnums=(0,) if donate else (),
    )


@lru_cache(maxsize=256)
def _program(op: str, mesh_id: int, fn: ReduceFunction, extra=None):
    mesh = _MESHES[mesh_id]
    spec = P(AXIS)

    if op == "allreduce":
        body = lambda x: collectives.allreduce(x[0], AXIS, fn)[None]
    elif op == "ring_allreduce":
        nseg = extra or 1
        body = lambda x: ring.ring_allreduce(x[0], AXIS, fn, nseg)[None]
    elif op == "pallas_allreduce":
        nseg, wire, bidir = extra  # (num_segments, wire_dtype_name, bidir)
        nseg = nseg or 1
        body = lambda x: pallas.ring_allreduce(
            x[0], AXIS, fn, nseg,
            bidirectional=bidir,
            wire_dtype=wire and jnp.dtype(wire),
        )[None]
    elif op == "compressed_allreduce":
        wire = jnp.dtype(extra or "bfloat16")
        body = lambda x: collectives.compressed_allreduce(
            x[0], AXIS, wire, fn
        )[None]
    elif op == "reduce":
        body = lambda x: collectives.reduce(x[0], AXIS, extra, fn)[None]
    elif op == "pallas_reduce":
        root, nseg = extra
        body = lambda x: pallas.ring_reduce(
            x[0], AXIS, root, fn, nseg or 1
        )[None]
    elif op == "pallas_bcast":
        root, nseg = extra
        body = lambda x: pallas.ring_bcast(x[0], AXIS, root, nseg or 1)[None]
    elif op == "pallas_scatter":
        root, nseg = extra
        body = lambda x: pallas.ring_scatter(
            x[0], AXIS, root, nseg or 1
        )[None]
    elif op == "pallas_gather":
        root, nseg = extra
        body = lambda x: pallas.ring_gather(
            x[0], AXIS, root, nseg or 1
        )[None]
    elif op == "reduce_scatter":
        body = lambda x: collectives.reduce_scatter(x[0], AXIS, fn, tiled=True)[None]
    elif op == "allgather":
        body = lambda x: collectives.allgather(x[0], AXIS, tiled=True)[None]
    elif op == "bcast":
        body = lambda x: collectives.bcast(x[0], AXIS, extra)[None]
    elif op == "bcast_inplace":
        # donating variant for the engine's device-resident in-place bcast
        # (op0 IS res on every rank); the public run_bcast never donates —
        # callers may hold the input array
        body = lambda x: collectives.bcast(x[0], AXIS, extra)[None]
        return _smap(mesh, body, (spec,), spec, donate=True)
    elif op == "scatter":
        body = lambda x: collectives.scatter(x[0], AXIS, extra)[None]
    elif op == "gather":
        body = lambda x: collectives.gather(x[0], AXIS, extra)[None]
    elif op == "alltoall":
        body = lambda x: collectives.alltoall(x[0], AXIS)[None]
    else:
        raise ValueError(op)
    return _smap(mesh, body, (spec,), spec)


_MESHES = {}


def _mesh_key(mesh: Mesh) -> int:
    key = id(mesh)
    _MESHES[key] = mesh
    return key


def _put(stacked, mesh: Mesh):
    sharding = NamedSharding(mesh, P(AXIS))
    if isinstance(stacked, jax.Array) and stacked.sharding == sharding:
        return stacked  # already assembled on the mesh: zero-copy passthrough
    stacked = jnp.asarray(stacked)
    return jax.device_put(stacked, sharding)


def run_allreduce(stacked, mesh: Mesh, function=ReduceFunction.SUM):
    """stacked[r] = rank r's operand; returns stacked results (identical
    rows).  One XLA all-reduce over the mesh axis."""
    return _program("allreduce", _mesh_key(mesh), function)(_put(stacked, mesh))


def run_ring_allreduce(
    stacked, mesh: Mesh, function=ReduceFunction.SUM, num_segments: int = 1
):
    """The explicit segmented-ring pipeline (algorithm-faithful mode)."""
    return _program("ring_allreduce", _mesh_key(mesh), function, num_segments)(
        _put(stacked, mesh)
    )


def run_pallas_allreduce(
    stacked,
    mesh: Mesh,
    function=ReduceFunction.SUM,
    num_segments: int = 1,
    wire_dtype: str = None,
    bidirectional: bool = False,
):
    """The segmented ring as a single Pallas kernel: remote-DMA hops over
    ICI with slot-ack flow control (interpreted off-TPU).  ``wire_dtype``
    (a dtype name string, to key the program cache) narrows the payload on
    the wire with in-kernel compress/decompress lanes; ``bidirectional``
    runs the operand's halves around the ring in opposite directions,
    using both ICI links of every neighbor pair."""
    return _program(
        "pallas_allreduce", _mesh_key(mesh), function,
        (num_segments, wire_dtype, bool(bidirectional)),
    )(_put(stacked, mesh))


def run_compressed_allreduce(
    stacked, mesh: Mesh, function=ReduceFunction.SUM, wire_dtype: str = "bfloat16"
):
    """Allreduce with operands narrowed to ``wire_dtype`` on the wire (the
    ETH_COMPRESSED analog); ``wire_dtype`` is a dtype name string so it can
    key the program cache."""
    return _program(
        "compressed_allreduce", _mesh_key(mesh), function, str(wire_dtype)
    )(_put(stacked, mesh))


def run_reduce(stacked, mesh: Mesh, root=0, function=ReduceFunction.SUM):
    return _program("reduce", _mesh_key(mesh), function, root)(_put(stacked, mesh))


def run_pallas_reduce(
    stacked, mesh: Mesh, root=0, function=ReduceFunction.SUM,
    num_segments: int = 1,
):
    """Reduce-to-root as the rooted Pallas ring pipeline (algorithm-
    faithful mode; only the root row of the result is meaningful)."""
    return _program(
        "pallas_reduce", _mesh_key(mesh), function, (root, num_segments)
    )(_put(stacked, mesh))


def run_pallas_bcast(stacked, mesh: Mesh, root=0, num_segments: int = 1):
    return _program(
        "pallas_bcast", _mesh_key(mesh), ReduceFunction.SUM,
        (root, num_segments),
    )(_put(stacked, mesh))


def run_pallas_scatter(stacked, mesh: Mesh, root=0, num_segments: int = 1):
    return _program(
        "pallas_scatter", _mesh_key(mesh), ReduceFunction.SUM,
        (root, num_segments),
    )(_put(stacked, mesh))


def run_pallas_gather(stacked, mesh: Mesh, root=0, num_segments: int = 1):
    """Gather via the ring relay (every row holds the full gather; the
    root's row is the result)."""
    return _program(
        "pallas_gather", _mesh_key(mesh), ReduceFunction.SUM,
        (root, num_segments),
    )(_put(stacked, mesh))


def run_reduce_scatter(stacked, mesh: Mesh, function=ReduceFunction.SUM):
    return _program("reduce_scatter", _mesh_key(mesh), function)(
        _put(stacked, mesh)
    )


def run_allgather(stacked, mesh: Mesh):
    return _program("allgather", _mesh_key(mesh), ReduceFunction.SUM)(
        _put(stacked, mesh)
    )


def run_bcast(stacked, mesh: Mesh, root=0, donate: bool = False):
    """``donate=True`` hands the input's HBM to XLA (in-place bcast); only
    safe when the caller no longer needs the input array."""
    op = "bcast_inplace" if donate else "bcast"
    return _program(op, _mesh_key(mesh), ReduceFunction.SUM, root)(
        _put(stacked, mesh)
    )


def run_scatter(stacked, mesh: Mesh, root=0):
    return _program("scatter", _mesh_key(mesh), ReduceFunction.SUM, root)(
        _put(stacked, mesh)
    )


def run_gather(stacked, mesh: Mesh, root=0):
    return _program("gather", _mesh_key(mesh), ReduceFunction.SUM, root)(
        _put(stacked, mesh)
    )


def run_alltoall(stacked, mesh: Mesh):
    return _program("alltoall", _mesh_key(mesh), ReduceFunction.SUM)(
        _put(stacked, mesh)
    )
