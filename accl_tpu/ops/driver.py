"""Host-level drivers: global arrays in, jitted SPMD collectives out.

The convention mirrors the test harness of the reference (per-rank operand
buffers): operands are *stacked* along a leading rank axis — ``stacked[r]``
is rank r's contribution — and results come back stacked the same way.
Under the hood each call builds (and caches) one jitted ``shard_map``
program over the mesh; on TPU the transfers ride ICI.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Optional

import jax

from ..compat import install as _compat_install

_compat_install()  # legacy-jax shims (shard_map kwargs, lax.axis_size)
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map  # type: ignore

from ..constants import ReduceFunction
from . import collectives, pallas, ring

AXIS = "ranks"


def make_mesh(n: Optional[int] = None, axis: str = AXIS) -> Mesh:
    devs = jax.devices()
    n = n or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    return Mesh(devs[:n], (axis,))


def _smap(mesh: Mesh, fn, in_spec, out_spec, donate: bool = False):
    return jax.jit(
        shard_map(
            fn,
            mesh=mesh,
            in_specs=in_spec,
            out_specs=out_spec,
            check_vma=False,
        ),
        # donation: in-place collectives (bcast writes its own operand) hand
        # their operand's HBM to XLA, the jax analog of the reference's
        # in-place device BOs
        donate_argnums=(0,) if donate else (),
    )


def _shard_fn(op: str, fn: ReduceFunction, extra=None):
    """Per-shard collective body for ``op`` — the building block both the
    single-op programs and the fused batch programs are traced from."""
    if op == "allreduce":
        sfn = lambda x: collectives.allreduce(x, AXIS, fn)
    elif op == "ring_allreduce":
        nseg = extra or 1
        sfn = lambda x: ring.ring_allreduce(x, AXIS, fn, nseg)
    elif op == "pallas_allreduce":
        nseg, wire, bidir = extra  # (num_segments, wire_dtype_name, bidir)
        nseg = nseg or 1
        sfn = lambda x: pallas.ring_allreduce(
            x, AXIS, fn, nseg,
            bidirectional=bidir,
            wire_dtype=wire and jnp.dtype(wire),
        )
    elif op == "compressed_allreduce":
        wire = jnp.dtype(extra or "bfloat16")
        sfn = lambda x: collectives.compressed_allreduce(x, AXIS, wire, fn)
    elif op == "reduce":
        sfn = lambda x: collectives.reduce(x, AXIS, extra, fn)
    elif op == "pallas_reduce":
        root, nseg = extra
        sfn = lambda x: pallas.ring_reduce(x, AXIS, root, fn, nseg or 1)
    elif op == "pallas_bcast":
        root, nseg = extra
        sfn = lambda x: pallas.ring_bcast(x, AXIS, root, nseg or 1)
    elif op == "pallas_scatter":
        root, nseg = extra
        sfn = lambda x: pallas.ring_scatter(x, AXIS, root, nseg or 1)
    elif op == "pallas_gather":
        root, nseg = extra
        sfn = lambda x: pallas.ring_gather(x, AXIS, root, nseg or 1)
    elif op == "reduce_scatter":
        sfn = lambda x: collectives.reduce_scatter(x, AXIS, fn, tiled=True)
    elif op == "allgather":
        sfn = lambda x: collectives.allgather(x, AXIS, tiled=True)
    elif op in ("bcast", "bcast_inplace"):
        # bcast_inplace: donating variant for the engine's device-resident
        # in-place bcast (op0 IS res on every rank); the public run_bcast
        # never donates — callers may hold the input array
        sfn = lambda x: collectives.bcast(x, AXIS, extra)
    elif op == "scatter":
        sfn = lambda x: collectives.scatter(x, AXIS, extra)
    elif op == "gather":
        sfn = lambda x: collectives.gather(x, AXIS, extra)
    elif op == "alltoall":
        sfn = lambda x: collectives.alltoall(x, AXIS)
    else:
        raise ValueError(op)
    return sfn


def _with_prep(sfn, prep):
    """Fuse operand staging INTO the collective body (single-interaction
    dispatch): ``prep = (take_w, wire_name)`` slices a rank's raw (w,)
    HBM shard down to the call width and applies the wire-dtype rounding
    lane inside the SAME program, so a width-slack or compressed operand
    costs no separate staging dispatch (the old ``_prep_program`` hop)."""
    if prep is None:
        return sfn
    take_w, wire_name = prep

    def fused(x):
        if take_w is not None and take_w != x.shape[0]:
            x = x[:take_w]
        if wire_name is not None:
            # the shared wire lane helper: covers the scaled int8 lane
            # (blockwise quantize round-trip) beside the plain cast
            # lanes; deterministic here — the prep spec is part of the
            # program-cache key and carries no per-call seed
            from . import wire as devwire

            x = devwire.wire_lane_roundtrip(x, jnp.dtype(wire_name))
        return sfn(x)

    return fused


@lru_cache(maxsize=256)
def _program(op: str, mesh_id: int, fn: ReduceFunction, extra=None,
             flat: bool = False, prep=None):
    """``flat=False``: operands/results are (size, w) stacked arrays (the
    host/test convention).  ``flat=True``: 1-D (size*w,) globals whose
    per-rank shards ARE raw (w,) device arrays — the engine's zero-dispatch
    path (a rank's HBM buffer plugs in as a shard with no reshape program,
    and result shards adopt straight into buffers).  ``prep`` (flat only)
    fuses per-shard staging into the program — see :func:`_with_prep`."""
    mesh = _MESHES[mesh_id]
    spec = P(AXIS)
    sfn = _shard_fn(op, fn, extra)
    if flat:
        body = _with_prep(sfn, prep)
    else:
        body = lambda x: sfn(x[0])[None]
    return _smap(mesh, body, (spec,), spec, donate=op == "bcast_inplace")


@lru_cache(maxsize=128)
def _batch_program(mesh_id: int, specs: tuple):
    """ONE jitted shard_map over a whole flushed command-queue batch:
    ``specs`` is a tuple of per-slot ``(op, fn, extra, prep, flat)``
    records; the program takes one global per slot and returns one output
    per slot.  N queued collectives therefore dispatch as a single device
    interaction — the batched analog of the reference's one-command-per-
    collective hostctrl discipline, amortized N:1."""
    mesh = _MESHES[mesh_id]
    spec = P(AXIS)
    bodies = []
    for op, fn, extra, prep, flat in specs:
        sfn = _shard_fn(op, fn, extra)
        if flat:
            bodies.append(_with_prep(sfn, prep))
        else:
            bodies.append(lambda x, sfn=sfn: sfn(x[0])[None])

    def body(*xs):
        return tuple(b(x) for b, x in zip(bodies, xs))

    n = len(specs)
    return _smap(mesh, body, (spec,) * n, (spec,) * n)


def run_batch(globals_, mesh: Mesh, specs) -> tuple:
    """Run a flushed batch: one global array per spec, one fused program,
    one dispatch.  ``specs`` as in :func:`_batch_program`."""
    return _batch_program(_mesh_key(mesh), tuple(specs))(
        *[_put(g, mesh) for g in globals_]
    )


_MESHES = {}


def _mesh_key(mesh: Mesh) -> int:
    key = id(mesh)
    _MESHES[key] = mesh
    return key


def _put(stacked, mesh: Mesh):
    sharding = NamedSharding(mesh, P(AXIS))
    if isinstance(stacked, jax.Array) and stacked.sharding == sharding:
        return stacked  # already assembled on the mesh: zero-copy passthrough
    stacked = jnp.asarray(stacked)
    return jax.device_put(stacked, sharding)


def _is_flat(stacked) -> bool:
    return getattr(stacked, "ndim", 2) == 1


def prepare(op: str, mesh: Mesh, function=ReduceFunction.SUM, extra=None,
            prep=None):
    """Prepared-program handle for an engine's plan cache: the jitted
    flat-layout program, to be invoked directly on an already-assembled
    global array (the caller owns the sharding guarantee).  Resolving it
    once per plan skips the per-call ``_put`` sharding construction/
    comparison and the lru key hashing the ``run_*`` entry points pay.

    The ``extra``-omitted call form matches the ``run_*`` entry points'
    convention exactly: lru_cache keys distinguish positional from
    keyword args, and a mismatched form would alias the SAME program
    under a second jit wrapper — a full recompile on the warm path."""
    if extra is None:
        return _program(op, _mesh_key(mesh), function, flat=True, prep=prep)
    return _program(op, _mesh_key(mesh), function, extra, flat=True,
                    prep=prep)


def run_allreduce(stacked, mesh: Mesh, function=ReduceFunction.SUM,
                  prep=None):
    """stacked[r] = rank r's operand; returns stacked results (identical
    rows).  One XLA all-reduce over the mesh axis.  A 1-D operand selects
    the flat layout (shards are raw per-rank arrays; see _program);
    ``prep`` fuses per-shard staging into the program (_with_prep)."""
    return _program(
        "allreduce", _mesh_key(mesh), function, flat=_is_flat(stacked),
        prep=prep,
    )(_put(stacked, mesh))


def run_ring_allreduce(
    stacked, mesh: Mesh, function=ReduceFunction.SUM, num_segments: int = 1,
    prep=None,
):
    """The explicit segmented-ring pipeline (algorithm-faithful mode)."""
    return _program(
        "ring_allreduce", _mesh_key(mesh), function, num_segments,
        flat=_is_flat(stacked), prep=prep,
    )(_put(stacked, mesh))


def run_pallas_allreduce(
    stacked,
    mesh: Mesh,
    function=ReduceFunction.SUM,
    num_segments: int = 1,
    wire_dtype: str = None,
    bidirectional: bool = False,
    prep=None,
):
    """The segmented ring as a single Pallas kernel: remote-DMA hops over
    ICI with slot-ack flow control (interpreted off-TPU).  ``wire_dtype``
    (a dtype name string, to key the program cache) narrows the payload on
    the wire with in-kernel compress/decompress lanes; ``bidirectional``
    runs the operand's halves around the ring in opposite directions,
    using both ICI links of every neighbor pair."""
    return _program(
        "pallas_allreduce", _mesh_key(mesh), function,
        (num_segments, wire_dtype, bool(bidirectional)),
        flat=_is_flat(stacked), prep=prep,
    )(_put(stacked, mesh))


def run_compressed_allreduce(
    stacked, mesh: Mesh, function=ReduceFunction.SUM,
    wire_dtype: str = "bfloat16", prep=None,
):
    """Allreduce with operands narrowed to ``wire_dtype`` on the wire (the
    ETH_COMPRESSED analog); ``wire_dtype`` is a dtype name string so it can
    key the program cache."""
    return _program(
        "compressed_allreduce", _mesh_key(mesh), function, str(wire_dtype),
        flat=_is_flat(stacked), prep=prep,
    )(_put(stacked, mesh))


def run_reduce(stacked, mesh: Mesh, root=0, function=ReduceFunction.SUM,
               prep=None):
    return _program(
        "reduce", _mesh_key(mesh), function, root, flat=_is_flat(stacked),
        prep=prep,
    )(_put(stacked, mesh))


def run_pallas_reduce(
    stacked, mesh: Mesh, root=0, function=ReduceFunction.SUM,
    num_segments: int = 1, prep=None,
):
    """Reduce-to-root as the rooted Pallas ring pipeline (algorithm-
    faithful mode; only the root row of the result is meaningful)."""
    return _program(
        "pallas_reduce", _mesh_key(mesh), function, (root, num_segments),
        flat=_is_flat(stacked), prep=prep,
    )(_put(stacked, mesh))


def run_pallas_bcast(stacked, mesh: Mesh, root=0, num_segments: int = 1,
                     prep=None):
    return _program(
        "pallas_bcast", _mesh_key(mesh), ReduceFunction.SUM,
        (root, num_segments), flat=_is_flat(stacked), prep=prep,
    )(_put(stacked, mesh))


def run_pallas_scatter(stacked, mesh: Mesh, root=0, num_segments: int = 1,
                       prep=None):
    return _program(
        "pallas_scatter", _mesh_key(mesh), ReduceFunction.SUM,
        (root, num_segments), flat=_is_flat(stacked), prep=prep,
    )(_put(stacked, mesh))


def run_pallas_gather(stacked, mesh: Mesh, root=0, num_segments: int = 1,
                      prep=None):
    """Gather via the ring relay (every row holds the full gather; the
    root's row is the result)."""
    return _program(
        "pallas_gather", _mesh_key(mesh), ReduceFunction.SUM,
        (root, num_segments), flat=_is_flat(stacked), prep=prep,
    )(_put(stacked, mesh))


def run_reduce_scatter(stacked, mesh: Mesh, function=ReduceFunction.SUM,
                       prep=None):
    return _program(
        "reduce_scatter", _mesh_key(mesh), function, flat=_is_flat(stacked),
        prep=prep,
    )(_put(stacked, mesh))


def run_allgather(stacked, mesh: Mesh, prep=None):
    return _program(
        "allgather", _mesh_key(mesh), ReduceFunction.SUM,
        flat=_is_flat(stacked), prep=prep,
    )(_put(stacked, mesh))


def run_bcast(stacked, mesh: Mesh, root=0, donate: bool = False,
              prep=None):
    """``donate=True`` hands the input's HBM to XLA (in-place bcast); only
    safe when the caller no longer needs the input array — never combined
    with ``prep`` width slack (the donated operand outlives the sliced
    result, so callers pass donate=False when prep is active)."""
    op = "bcast_inplace" if donate and prep is None else "bcast"
    return _program(
        op, _mesh_key(mesh), ReduceFunction.SUM, root,
        flat=_is_flat(stacked), prep=prep,
    )(_put(stacked, mesh))


def run_scatter(stacked, mesh: Mesh, root=0, prep=None):
    return _program(
        "scatter", _mesh_key(mesh), ReduceFunction.SUM, root,
        flat=_is_flat(stacked), prep=prep,
    )(_put(stacked, mesh))


def run_gather(stacked, mesh: Mesh, root=0, prep=None):
    return _program(
        "gather", _mesh_key(mesh), ReduceFunction.SUM, root,
        flat=_is_flat(stacked), prep=prep,
    )(_put(stacked, mesh))


def run_alltoall(stacked, mesh: Mesh, prep=None):
    return _program(
        "alltoall", _mesh_key(mesh), ReduceFunction.SUM,
        flat=_is_flat(stacked), prep=prep,
    )(_put(stacked, mesh))
