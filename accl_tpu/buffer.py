"""Buffer abstraction: host/device arrays the engine moves data between.

Role model: ``driver/xrt/include/accl/buffer.hpp:32-141`` (``BaseBuffer`` with
``sync_to_device`` / ``sync_from_device`` / ``slice`` / ``address`` /
``is_host_only``) and its backend implementations (XRTBuffer / SimBuffer /
DummyBuffer).  TPU-natively, "device memory" is TPU HBM addressed through JAX
arrays; on the emulator tier the device side is a distinct host allocation so
that sync semantics stay observable (a test can detect a missing sync exactly
like the reference suite does).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .constants import DataType, dtype_to_numpy, numpy_to_dtype


class BaseBuffer:
    """A typed 1-D region with a host view and a device residence."""

    def __init__(self, count: int, dtype: DataType):
        self._count = int(count)
        self._dtype = DataType(dtype)

    # -- introspection ------------------------------------------------------
    @property
    def count(self) -> int:
        return self._count

    @property
    def dtype(self) -> DataType:
        return self._dtype

    @property
    def nbytes(self) -> int:
        return self._count * dtype_to_numpy(self._dtype).itemsize

    @property
    def is_dummy(self) -> bool:
        return False

    @property
    def is_host_only(self) -> bool:
        return False

    # -- data movement ------------------------------------------------------
    def sync_to_device(self) -> None:
        raise NotImplementedError

    def sync_from_device(self) -> None:
        raise NotImplementedError

    def free_buffer(self) -> None:
        pass

    # -- views --------------------------------------------------------------
    def slice(self, start: int, stop: int) -> "BaseBuffer":
        raise NotImplementedError

    def host_view(self) -> np.ndarray:
        """Host-side numpy view (mutating it mutates host memory)."""
        raise NotImplementedError

    def device_view(self) -> np.ndarray:
        """Engine-side view of device memory (emulator tiers only)."""
        raise NotImplementedError


class EmuBuffer(BaseBuffer):
    """Emulator-tier buffer: host and 'device' are separate host allocations.

    The engine dataplane only ever touches ``device_view()``; user code writes
    ``host_view()`` (or the ``data`` property) and must ``sync_to_device`` —
    exactly the contract the reference tests rely on.  Slices alias the parent
    storage on both sides.
    """

    def __init__(
        self,
        count: int,
        dtype: DataType,
        host: Optional[np.ndarray] = None,
        dev: Optional[np.ndarray] = None,
        host_only: bool = False,
    ):
        super().__init__(count, dtype)
        npdt = dtype_to_numpy(dtype)
        self._host = host if host is not None else np.zeros(count, npdt)
        if host_only:
            self._dev = self._host
        else:
            self._dev = dev if dev is not None else np.zeros(count, npdt)
        self._host_only = host_only

    @classmethod
    def from_array(cls, arr: np.ndarray, host_only: bool = False) -> "EmuBuffer":
        arr = np.ascontiguousarray(arr).reshape(-1)
        return cls(arr.size, numpy_to_dtype(arr.dtype), host=arr, host_only=host_only)

    @property
    def is_host_only(self) -> bool:
        return self._host_only

    @property
    def data(self) -> np.ndarray:
        return self._host

    def sync_to_device(self) -> None:
        if not self._host_only:
            np.copyto(self._dev, self._host)

    def sync_from_device(self) -> None:
        if not self._host_only:
            np.copyto(self._host, self._dev)

    def slice(self, start: int, stop: int) -> "EmuBuffer":
        if not (0 <= start <= stop <= self._count):
            raise IndexError(f"slice [{start}:{stop}) out of range 0..{self._count}")
        return EmuBuffer(
            stop - start,
            self._dtype,
            host=self._host[start:stop],
            dev=self._dev[start:stop],
            host_only=self._host_only,
        )

    def host_view(self) -> np.ndarray:
        return self._host

    def device_view(self) -> np.ndarray:
        return self._dev


class DummyBuffer(BaseBuffer):
    """Placeholder operand for ranks that contribute no data to a collective
    (ref ``driver/xrt/include/accl/dummybuffer.hpp``)."""

    def __init__(self, count: int = 0, dtype: DataType = DataType.FLOAT32):
        super().__init__(count, dtype)

    @property
    def is_dummy(self) -> bool:
        return True

    def sync_to_device(self) -> None:
        pass

    def sync_from_device(self) -> None:
        pass

    def slice(self, start: int, stop: int) -> "DummyBuffer":
        return DummyBuffer(stop - start, self._dtype)

    def host_view(self) -> np.ndarray:
        raise RuntimeError("dummy buffer has no storage")

    def device_view(self) -> np.ndarray:
        raise RuntimeError("dummy buffer has no storage")
