"""Buffer abstraction: host/device arrays the engine moves data between.

Role model: ``driver/xrt/include/accl/buffer.hpp:32-141`` (``BaseBuffer`` with
``sync_to_device`` / ``sync_from_device`` / ``slice`` / ``address`` /
``is_host_only``) and its backend implementations (XRTBuffer / SimBuffer /
DummyBuffer).  TPU-natively, "device memory" is TPU HBM addressed through JAX
arrays; on the emulator tier the device side is a distinct host allocation so
that sync semantics stay observable (a test can detect a missing sync exactly
like the reference suite does).
"""

from __future__ import annotations

import functools
import threading
from typing import Optional

import numpy as np

from .constants import DataType, dtype_to_numpy, numpy_to_dtype


@functools.lru_cache(maxsize=512)
def _zeros_program(shape: tuple, npdt, device):
    """Jitted on-device zeros initializer, cached per (shape, dtype, device)
    so repeated buffer creation reuses the compiled program.  Shared with
    the XLA engine's dummy-operand shards."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import SingleDeviceSharding

    return jax.jit(
        lambda: jnp.zeros(shape, npdt),
        out_shardings=SingleDeviceSharding(device),
    )


def dev_zeros(shape: tuple, npdt, device):
    """A zeros array committed to ``device`` without touching the host."""
    return _zeros_program(tuple(shape), np.dtype(npdt), device)()


def make_buffer(device, count: int, dtype, host_only: bool = False,
                data=None):
    """Backend-appropriate buffer for a device-tier engine: an HBM-resident
    :class:`DeviceBuffer` on ``device``, or an :class:`EmuBuffer` when
    host-only (or no device is available).  ``data`` seeds the buffer —
    the host side ALIASES it and the device side is synced on return."""
    if host_only or device is None:
        if data is not None:
            buf = EmuBuffer.from_array(data, host_only=host_only)
            buf.sync_to_device()
            return buf
        return EmuBuffer(count, dtype, host_only=host_only)
    if data is not None:
        import jax

        arr = jax.device_put(data, device)
        return DeviceBuffer(count, dtype, device, array=arr, host=data)
    return DeviceBuffer(count, dtype, device)


# Slicing and scatter-writeback run as cached jitted programs, not eager
# ops: eager indexing dispatches its index scalars host->device, which
# would violate the zero-host-copy contract (and trip transfer guards).
@functools.lru_cache(maxsize=2048)
def _slice_program(start: int, stop: int):
    import jax

    return jax.jit(lambda a: a[start:stop])


@functools.lru_cache(maxsize=2048)
def _writeback_program(start: int, n: int):
    import jax

    return jax.jit(lambda base, a: base.at[start : start + n].set(a[:n]))


class BaseBuffer:
    """A typed 1-D region with a host view and a device residence."""

    def __init__(self, count: int, dtype: DataType):
        self._count = int(count)
        self._dtype = DataType(dtype)

    # -- introspection ------------------------------------------------------
    @property
    def count(self) -> int:
        return self._count

    @property
    def dtype(self) -> DataType:
        return self._dtype

    @property
    def nbytes(self) -> int:
        return self._count * dtype_to_numpy(self._dtype).itemsize

    @property
    def is_dummy(self) -> bool:
        return False

    @property
    def is_host_only(self) -> bool:
        return False

    # -- data movement ------------------------------------------------------
    def sync_to_device(self) -> None:
        raise NotImplementedError

    def sync_from_device(self) -> None:
        raise NotImplementedError

    def free_buffer(self) -> None:
        pass

    # -- views --------------------------------------------------------------
    def slice(self, start: int, stop: int) -> "BaseBuffer":
        raise NotImplementedError

    def host_view(self) -> np.ndarray:
        """Host-side numpy view (mutating it mutates host memory)."""
        raise NotImplementedError

    def device_view(self) -> np.ndarray:
        """Engine-side view of device memory (emulator tiers only)."""
        raise NotImplementedError


class EmuBuffer(BaseBuffer):
    """Emulator-tier buffer: host and 'device' are separate host allocations.

    The engine dataplane only ever touches ``device_view()``; user code writes
    ``host_view()`` (or the ``data`` property) and must ``sync_to_device`` —
    exactly the contract the reference tests rely on.  Slices alias the parent
    storage on both sides.
    """

    def __init__(
        self,
        count: int,
        dtype: DataType,
        host: Optional[np.ndarray] = None,
        dev: Optional[np.ndarray] = None,
        host_only: bool = False,
    ):
        super().__init__(count, dtype)
        npdt = dtype_to_numpy(dtype)
        self._host = host if host is not None else np.zeros(count, npdt)
        if host_only:
            self._dev = self._host
        else:
            self._dev = dev if dev is not None else np.zeros(count, npdt)
        self._host_only = host_only

    @classmethod
    def from_array(cls, arr: np.ndarray, host_only: bool = False) -> "EmuBuffer":
        arr = np.ascontiguousarray(arr).reshape(-1)
        return cls(arr.size, numpy_to_dtype(arr.dtype), host=arr, host_only=host_only)

    @property
    def is_host_only(self) -> bool:
        return self._host_only

    @property
    def data(self) -> np.ndarray:
        return self._host

    def sync_to_device(self) -> None:
        if not self._host_only:
            np.copyto(self._dev, self._host)

    def sync_from_device(self) -> None:
        if not self._host_only:
            np.copyto(self._host, self._dev)

    def slice(self, start: int, stop: int) -> "EmuBuffer":
        if not (0 <= start <= stop <= self._count):
            raise IndexError(f"slice [{start}:{stop}) out of range 0..{self._count}")
        return EmuBuffer(
            stop - start,
            self._dtype,
            host=self._host[start:stop],
            dev=self._dev[start:stop],
            host_only=self._host_only,
        )

    def host_view(self) -> np.ndarray:
        return self._host

    def device_view(self) -> np.ndarray:
        return self._dev


class DeviceBuffer(BaseBuffer):
    """HBM-resident buffer: the device side is a committed ``jax.Array``.

    Role model: ``XRTBuffer`` (``driver/xrt/include/accl/xrtbuffer.hpp``) —
    a device BO with a host shadow and ``sync_to/from_device``.  On TPU the
    BO is a single-device ``jax.Array`` pinned to one chip's HBM; the
    collective engine assembles per-rank device arrays into one sharded
    global array with ``jax.make_array_from_single_device_arrays`` (zero
    copy) and adopts result shards back — the host never touches the data
    path, matching the reference's "no host in the loop" contract
    (``README.md:7-14``, hot path ``accl.cpp:780-826``).

    jax.Arrays are immutable, so "writes" replace the underlying array
    (``store``) — a device-side computation, never a host transfer.  Slices
    carry a parent link and write back with ``.at[...].set`` on store,
    preserving the reference's aliasing semantics.
    """

    def __init__(
        self,
        count: int,
        dtype: DataType,
        device,
        array=None,
        parent: Optional["DeviceBuffer"] = None,
        offset: int = 0,
        host: Optional[np.ndarray] = None,
    ):
        super().__init__(count, dtype)
        self.device = device
        self._parent = parent
        self._offset = int(offset)
        # lazy result adoption (single-interaction dispatch): an engine
        # may park the device program that places a result into this
        # buffer (writeback/trim — one tunnel RTT each) as a pending
        # thunk; any data access resolves it first, so fire-and-forget
        # callers never pay the result leg and readers never see stale
        # bytes.  Lives on the ROOT buffer (stores write through parents);
        # the REENTRANT lock makes park/resolve atomic AND ordered: a
        # concurrent resolver that loses the race blocks until the
        # winner's thunk has fully landed (so no reader can observe the
        # pre-store _dev), while the thunk's own store()/device_array()
        # re-entering resolve_pending on the same thread cannot deadlock.
        self._pending: Optional[object] = None
        self._plock = threading.RLock()
        # monotone defer counter: every parked thunk bumps it, so a
        # writer that wants to COLLAPSE successive whole-result stores
        # (the command ring's window adoption) can prove no other
        # deferred write slipped in between (buffer.py stays policy-
        # free: chaining remains the default — partial writes must
        # layer in issue order)
        self._defer_seq = 0
        npdt = dtype_to_numpy(dtype)
        self._host = host if host is not None else np.zeros(count, npdt)
        if parent is not None:
            self._dev = None  # storage lives in the root buffer
        elif array is not None:
            self._dev = array
        else:
            # allocate by committing the freshly-zeroed host shadow: one
            # H2D put, NO compile.  dev_zeros would jit a zeros program
            # per distinct count — a workload sweeping sizes (the soak)
            # pays a fresh XLA compile per allocation, which dominated
            # the round-4 dist soak.  Allocation is not the data path:
            # the zero-host-copy contract (transfer-guard-tested) covers
            # the collective between creation and sync, not creation.
            import jax

            self._dev = jax.device_put(self._host, device)

    # -- introspection ------------------------------------------------------
    @property
    def data(self) -> np.ndarray:
        return self._host

    def _root(self) -> "DeviceBuffer":
        buf = self
        while buf._parent is not None:
            buf = buf._parent
        return buf

    def _root_offset(self) -> int:
        buf, off = self, 0
        while buf._parent is not None:
            off += buf._offset
            buf = buf._parent
        return off

    def defer_store(self, thunk) -> None:
        """Park a result-placement thunk (engine side).  Chains with any
        earlier pending store so partial writes land in issue order when
        the buffer is finally resolved."""
        root = self._root()
        with root._plock:
            root._defer_seq += 1
            prev = root._pending
            if prev is None:
                root._pending = thunk
            else:
                def chained(prev=prev, thunk=thunk):
                    prev()
                    thunk()

                root._pending = chained

    def resolve_pending(self) -> None:
        """Run any parked result placement (idempotent; re-entrancy safe:
        the thunk's own ``store()`` sees the slot already cleared).  The
        thunk runs INSIDE the reentrant lock so a concurrent resolver
        that loses the swap cannot proceed to read ``_dev`` until the
        winner's store has landed."""
        root = self._root()
        with root._plock:
            thunk, root._pending = root._pending, None
            if thunk is not None:
                thunk()

    def device_array(self):
        """The committed ``jax.Array`` (sliced view for child buffers —
        a device-side computation, not a transfer)."""
        self.resolve_pending()
        root = self._root()
        if root is self:
            return self._dev
        off = self._root_offset()
        return _slice_program(off, off + self._count)(root._dev)

    def store(self, array, count: Optional[int] = None) -> bool:
        """Engine-side result placement: replace the first ``count`` device
        elements with ``array`` (a jax.Array already on this device).
        Whole-buffer stores on root buffers are free (pointer swap); partial
        or sliced stores write back with ``.at[...].set``.  Returns True
        when a writeback program was dispatched (a device interaction),
        False for the free pointer swap — the engines' interaction
        counters key off this."""
        self.resolve_pending()
        n = self._count if count is None else int(count)
        if getattr(array, "ndim", 1) != 1 or array.shape[0] < n:
            raise ValueError(
                f"store of shape {getattr(array, 'shape', '?')} into {n} "
                f"elements of a {self._count}-element buffer"
            )
        if array.dtype != dtype_to_numpy(self._dtype):
            raise TypeError(
                f"store dtype {array.dtype} != buffer dtype {self._dtype.name}"
            )
        root = self._root()
        off = self._root_offset()
        if root is self and n == self._count and array.shape[0] == n:
            root._dev = array
            return False
        root._dev = _writeback_program(off, n)(root._dev, array)
        return True

    # -- data movement ------------------------------------------------------
    def sync_to_device(self) -> None:
        import jax

        arr = jax.device_put(self._host, self.device)
        self.store(arr)

    def sync_from_device(self) -> None:
        np.copyto(self._host, np.asarray(self.device_array()))

    def free_buffer(self) -> None:
        root = self._root()
        if root is self:
            # only the ROOT free drops parked results (they are moot once
            # the storage dies); freeing a child slice must not discard a
            # deferred store destined for the root or a sibling slice
            with root._plock:
                root._pending = None
            if self._dev is not None:
                self._dev.delete()
                self._dev = None

    # -- views --------------------------------------------------------------
    def slice(self, start: int, stop: int) -> "DeviceBuffer":
        if not (0 <= start <= stop <= self._count):
            raise IndexError(f"slice [{start}:{stop}) out of range 0..{self._count}")
        return DeviceBuffer(
            stop - start,
            self._dtype,
            self.device,
            parent=self,
            offset=start,
            host=self._host[start:stop],
        )

    def host_view(self) -> np.ndarray:
        return self._host

    def device_view(self) -> np.ndarray:
        """Host copy of device memory — the generic fallback path for mixed
        emulator/device operands.  The zero-copy engine path never calls
        this (it uses :meth:`device_array`)."""
        return np.asarray(self.device_array())


class DummyBuffer(BaseBuffer):
    """Placeholder operand for ranks that contribute no data to a collective
    (ref ``driver/xrt/include/accl/dummybuffer.hpp``)."""

    def __init__(self, count: int = 0, dtype: DataType = DataType.FLOAT32):
        super().__init__(count, dtype)

    @property
    def is_dummy(self) -> bool:
        return True

    def sync_to_device(self) -> None:
        pass

    def sync_from_device(self) -> None:
        pass

    def slice(self, start: int, stop: int) -> "DummyBuffer":
        return DummyBuffer(stop - start, self._dtype)

    def host_view(self) -> np.ndarray:
        raise RuntimeError("dummy buffer has no storage")

    def device_view(self) -> np.ndarray:
        raise RuntimeError("dummy buffer has no storage")
